"""GLM objective kernel tests: fused value/grad/Hv/Hdiag/Hmat vs jax autodiff
and an independent dense numpy implementation, dense vs ELL-sparse parity,
normalization algebra, and weighted/offset semantics.

Mirrors the reference's aggregator + OptimizationProblemIntegTestUtils
cross-check strategy (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import (
    GLMObjective,
    LOGISTIC,
    POISSON,
    SQUARED,
    batch_from_coo,
    batch_from_dense,
    build_normalization,
    compute_variances,
)
from photon_ml_tpu.ops import losses as L


def make_problem(rng, n=64, d=7, loss=LOGISTIC):
    x = rng.normal(size=(n, d))
    w_true = rng.normal(size=d)
    logits = x @ w_true
    if loss is LOGISTIC:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(float)
    elif loss is POISSON:
        y = rng.poisson(np.exp(np.clip(logits, -3, 3))).astype(float)
    else:
        y = logits + rng.normal(size=n)
    offs = rng.normal(size=n) * 0.1
    wts = rng.uniform(0.5, 2.0, size=n)
    return x, y, offs, wts


def reference_value_grad(loss_name, x, y, offs, wts, w, l2=0.0):
    """Independent numpy implementation."""
    z = x @ w + offs
    if loss_name == "logistic":
        val = np.sum(wts * (np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - y * z))
        dz = 1 / (1 + np.exp(-z)) - y
    elif loss_name == "squared":
        val = np.sum(wts * 0.5 * (z - y) ** 2)
        dz = z - y
    elif loss_name == "poisson":
        val = np.sum(wts * (np.exp(z) - y * z))
        dz = np.exp(z) - y
    grad = x.T @ (wts * dz)
    return val + 0.5 * l2 * w @ w, grad + l2 * w


@pytest.mark.parametrize("loss", [LOGISTIC, SQUARED, POISSON])
def test_value_and_grad_vs_numpy(rng, loss):
    x, y, offs, wts = make_problem(rng, loss=loss)
    w = rng.normal(size=x.shape[1]) * 0.3
    batch = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    obj = GLMObjective(loss=loss, batch=batch, l2=0.7)
    v, g = obj.value_and_grad(jnp.asarray(w))
    rv, rg = reference_value_grad(loss.name, x, y, offs, wts, w, l2=0.7)
    np.testing.assert_allclose(float(v), rv, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(g), rg, rtol=1e-9)


def test_grad_matches_autodiff(rng):
    x, y, offs, wts = make_problem(rng)
    batch = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch, l2=0.3)
    w = jnp.asarray(rng.normal(size=x.shape[1]))
    auto = jax.grad(obj.value)(w)
    _, fused = obj.value_and_grad(w)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(auto), rtol=1e-10)


def test_sparse_dense_parity(rng):
    n, d = 40, 12
    dense = rng.normal(size=(n, d)) * (rng.uniform(size=(n, d)) < 0.3)
    y = (rng.uniform(size=n) < 0.5).astype(float)
    rows, cols = np.nonzero(dense)
    vals = dense[rows, cols]
    b_dense = batch_from_dense(dense, y, dtype=jnp.float64)
    b_sparse = batch_from_coo(rows, cols, vals, y, dim=d, dtype=jnp.float64)
    w = jnp.asarray(rng.normal(size=d))
    for loss in (LOGISTIC, SQUARED):
        od, os_ = (GLMObjective(loss=loss, batch=b) for b in (b_dense, b_sparse))
        vd, gd = od.value_and_grad(w)
        vs, gs = os_.value_and_grad(w)
        np.testing.assert_allclose(float(vd), float(vs), rtol=1e-10)
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gs), rtol=1e-9)
        v = jnp.asarray(rng.normal(size=d))
        np.testing.assert_allclose(
            np.asarray(od.hessian_vector(w, v)),
            np.asarray(os_.hessian_vector(w, v)),
            rtol=1e-9,
        )
        np.testing.assert_allclose(
            np.asarray(od.hessian_diagonal(w)),
            np.asarray(os_.hessian_diagonal(w)),
            rtol=1e-9,
        )


def test_hessian_vector_matches_autodiff(rng):
    x, y, offs, wts = make_problem(rng)
    batch = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch, l2=0.2)
    w = jnp.asarray(rng.normal(size=x.shape[1]))
    v = jnp.asarray(rng.normal(size=x.shape[1]))
    hv_auto = jax.jvp(lambda c: jax.grad(obj.value)(c), (w,), (v,))[1]
    np.testing.assert_allclose(
        np.asarray(obj.hessian_vector(w, v)), np.asarray(hv_auto), rtol=1e-8
    )


def test_hessian_matrix_and_diag_consistent(rng):
    x, y, offs, wts = make_problem(rng, n=32, d=5)
    batch = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch, l2=0.1)
    w = jnp.asarray(rng.normal(size=5))
    h = np.asarray(obj.hessian_matrix(w))
    # full Hessian via autodiff
    h_auto = np.asarray(jax.hessian(obj.value)(w))
    np.testing.assert_allclose(h, h_auto, rtol=1e-8)
    np.testing.assert_allclose(np.asarray(obj.hessian_diagonal(w)), np.diag(h), rtol=1e-8)
    # Hv consistency
    v = jnp.asarray(np.ones(5))
    np.testing.assert_allclose(np.asarray(obj.hessian_vector(w, v)), h @ np.ones(5), rtol=1e-8)


def test_normalization_margin_invariance(rng):
    """Objective in transformed space with raw data == objective on explicitly
    normalized data (the whole point of the effective-coefficient algebra)."""
    n, d = 50, 6
    x = rng.normal(size=(n, d)) * 3 + 1.5
    x[:, -1] = 1.0  # intercept column
    y = (rng.uniform(size=n) < 0.5).astype(float)
    means, var = x.mean(0), x.var(0)
    norm = build_normalization(
        "STANDARDIZATION", means, var, np.abs(x).max(0), intercept_index=d - 1,
        dtype=jnp.float64,
    )
    w_t = jnp.asarray(rng.normal(size=d))

    batch_raw = batch_from_dense(x, y, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch_raw, norm=norm)
    v_impl, g_impl = obj.value_and_grad(w_t)

    # explicit normalization: x' = (x - shift) * factor
    factors = np.asarray(norm.factors)
    shifts = np.asarray(norm.shifts)
    x_norm = (x - shifts) * factors
    v_ref, g_ref = reference_value_grad("logistic", x_norm, y, np.zeros(n), np.ones(n), np.asarray(w_t))
    np.testing.assert_allclose(float(v_impl), v_ref, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(g_impl), g_ref, rtol=1e-8)

    # Hv parity under normalization too
    v = jnp.asarray(rng.normal(size=d))
    obj_norm = GLMObjective(loss=LOGISTIC, batch=batch_from_dense(x_norm, y, dtype=jnp.float64))
    np.testing.assert_allclose(
        np.asarray(obj.hessian_vector(w_t, v)),
        np.asarray(obj_norm.hessian_vector(w_t, v)),
        rtol=1e-8,
    )
    np.testing.assert_allclose(
        np.asarray(obj.hessian_diagonal(w_t)),
        np.asarray(obj_norm.hessian_diagonal(w_t)),
        rtol=1e-8,
    )


def test_model_space_round_trip(rng):
    d = 6
    x = rng.normal(size=(8, d))
    x[:, 0] = 1.0
    norm = build_normalization(
        "STANDARDIZATION", x.mean(0), x.var(0) + 0.5, np.abs(x).max(0), intercept_index=0,
        dtype=jnp.float64,
    )
    w = jnp.asarray(rng.normal(size=d))
    back = norm.model_to_transformed_space(norm.model_to_original_space(w))
    np.testing.assert_allclose(np.asarray(back), np.asarray(w), rtol=1e-12)
    # margin invariance: w'.x' == w.x for w = toOriginal(w')
    w_orig = norm.model_to_original_space(w)
    x_norm = (x - np.asarray(norm.shifts)) * np.asarray(norm.factors)
    np.testing.assert_allclose(x_norm @ np.asarray(w), x @ np.asarray(w_orig), rtol=1e-10)


def test_zero_weight_rows_are_invisible(rng):
    x, y, offs, wts = make_problem(rng, n=20)
    batch_full = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    wts2 = wts.copy()
    wts2[10:] = 0.0
    batch_masked = batch_from_dense(x, y, offs, wts2, dtype=jnp.float64)
    batch_small = batch_from_dense(x[:10], y[:10], offs[:10], wts[:10], dtype=jnp.float64)
    w = jnp.asarray(rng.normal(size=x.shape[1]))
    om = GLMObjective(loss=LOGISTIC, batch=batch_masked)
    os_ = GLMObjective(loss=LOGISTIC, batch=batch_small)
    np.testing.assert_allclose(float(om.value(w)), float(os_.value(w)), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(om.gradient(w)), np.asarray(os_.gradient(w)), rtol=1e-10
    )


def test_variances(rng):
    x, y, offs, wts = make_problem(rng, n=200, d=4)
    batch = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch, l2=1.0)
    w = jnp.asarray(rng.normal(size=4))
    assert compute_variances(obj, w, "NONE") is None
    simple = compute_variances(obj, w, "SIMPLE")
    full = compute_variances(obj, w, "FULL")
    h = np.asarray(obj.hessian_matrix(w))
    np.testing.assert_allclose(np.asarray(simple), 1 / np.diag(h), rtol=1e-8)
    np.testing.assert_allclose(np.asarray(full), np.diag(np.linalg.inv(h)), rtol=1e-8)


def test_full_variance_zero_activity_column_unregularized(rng):
    """A real-but-zero-activity feature column with l2=0 leaves a zero
    row/col in the Hessian; the zero diagonal is pinned to 1 (the SIMPLE
    convention) so FULL variances stay finite and the active block's
    variances are untouched (ADVICE r4: ops/glm.py singular-inverse)."""
    x, y, offs, wts = make_problem(rng, n=200, d=4)
    x = np.asarray(x).copy()
    x[:, 2] = 0.0
    batch = batch_from_dense(x, y, offs, wts, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch, l2=0.0)
    w = jnp.zeros(4)
    full = np.asarray(compute_variances(obj, w, "FULL"))
    assert np.all(np.isfinite(full))
    assert full[2] == 1.0
    # active-block variances equal the dense-submatrix inverse
    keep = [0, 1, 3]
    h = np.asarray(obj.hessian_matrix(w))[np.ix_(keep, keep)]
    np.testing.assert_allclose(full[keep], np.diag(np.linalg.inv(h)), rtol=1e-8)
