"""Test harness configuration.

Mirrors the reference's SparkTestUtils strategy (photon-test-utils
.../SparkTestUtils.scala:58-76): "distributed" behavior is tested without a
cluster by running the real collective code paths on local devices. Here the
local cluster is a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), and float64 is enabled so math
tests can compare against scipy at tight tolerances.

These env vars MUST be set before jax is imported anywhere.
"""

import os

# Force CPU: the surrounding environment may point JAX at a real accelerator
# (e.g. JAX_PLATFORMS=axon, which also ignores later env-var edits — the
# config update below is what actually pins the platform).
os.environ["JAX_PLATFORMS"] = "cpu"
# Tests must not share the driver's persistent XLA compilation cache: a
# cache entry corrupted by a killed process SEGFAULTS jax's cache read
# (observed: compilation_cache.get_executable_and_time), and test compiles
# would pollute the production cache anyway. Plain assignment — a developer's
# exported PHOTON_COMPILE_CACHE must not leak into test runs.
os.environ["PHOTON_COMPILE_CACHE"] = "0"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """XLA:CPU's compiler segfaults nondeterministically deep into long
    single-process sessions (observed twice: round 4 at test_scale_paths with
    device-created pjit inputs, round 5 in backend_compile after ~160 tests).
    Dropping compiled executables between test modules resets the accumulated
    compiler state that triggers it; per-module granularity keeps the
    recompile cost bounded (shared solver jits are mostly reused within one
    module)."""
    yield
    jax.clear_caches()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test (multi-process smoke, scale paths)")
