"""Native C++ Avro columnar decoder: exact parity with the pure-Python codec
on every surface the GAME reader uses (labels/offsets/weights under numeric
unions, uid, id tags from top-level and metadataMap, multiple feature bags,
deflate + null codecs, row windows), plus fallback and error behavior."""

import os

import numpy as np
import pytest

from photon_ml_tpu import native
from photon_ml_tpu.io import (
    FeatureShardConfig,
    read_avro_dataset,
    write_avro_file,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native decoder not built (no g++/zlib)"
)


def _dense(ds, s):
    r, c, v = ds.shard_coo[s]
    x = np.zeros((ds.n_rows, ds.shard_dims[s]))
    x[r, c] = v
    return x


def _assert_dataset_equal(a, b, shards):
    assert a.n_rows == b.n_rows
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.offsets, b.offsets)
    np.testing.assert_array_equal(a.weights, b.weights)
    assert list(a.uids) == list(b.uids)
    assert set(a.id_tags) == set(b.id_tags)
    for t in a.id_tags:
        assert list(a.id_tags[t]) == list(b.id_tags[t])
    for s in shards:
        np.testing.assert_array_equal(_dense(a, s), _dense(b, s))


@pytest.fixture(scope="module")
def game_avro(tmp_path_factory):
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing import (
        generate_game_records,
        generate_mixed_effect_data,
    )

    data = generate_mixed_effect_data(
        n=700, d_fixed=6, re_specs={"userId": (20, 3)}, seed=5
    )
    recs = generate_game_records(data)
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    d = tmp_path_factory.mktemp("native")
    p = str(d / "game.avro")
    write_avro_file(p, schema, recs)
    return p


SHARDS = {
    "global": FeatureShardConfig(("features",)),
    "user": FeatureShardConfig(("userFeatures",)),
}


def test_native_matches_python_end_to_end(game_avro):
    py, im_py = read_avro_dataset(
        game_avro, SHARDS, id_tag_columns=["userId"], engine="python"
    )
    nat, im_nat = read_avro_dataset(
        game_avro, SHARDS, id_tag_columns=["userId"], engine="native"
    )
    for s in SHARDS:
        assert sorted(im_nat[s].keys()) == sorted(im_py[s].keys())
    _assert_dataset_equal(nat, py, SHARDS)


def test_native_row_window_matches_python(game_avro):
    _, imaps = read_avro_dataset(game_avro, SHARDS, engine="python")
    for rng in [(0, 700), (123, 456), (650, 700), (0, 1)]:
        py, _ = read_avro_dataset(
            game_avro, SHARDS, index_maps=imaps, id_tag_columns=["userId"],
            row_range=rng, engine="python",
        )
        nat, _ = read_avro_dataset(
            game_avro, SHARDS, index_maps=imaps, id_tag_columns=["userId"],
            row_range=rng, engine="native",
        )
        _assert_dataset_equal(nat, py, SHARDS)


def test_native_legacy_union_shapes(tmp_path):
    """Legacy metronome shape: numeric-union label/weight/offset, nullable
    term, null codec, id tag only in metadataMap."""
    schema = {
        "type": "record",
        "name": "TrainingExample",
        "fields": [
            {"name": "label", "type": ["int", "double"]},
            {"name": "weight", "type": ["null", "float"], "default": None},
            {"name": "offset", "type": ["null", "long"], "default": None},
            {"name": "uid", "type": ["null", "string"], "default": None},
            {
                "name": "metadataMap",
                "type": ["null", {"type": "map", "values": "string"}],
                "default": None,
            },
            {
                "name": "features",
                "type": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "FeatureAvro",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": ["null", "string"]},
                            {"name": "value", "type": ["int", "double"]},
                        ],
                    },
                },
            },
        ],
    }
    recs = [
        {
            "label": 1, "weight": 2.5, "offset": 3, "uid": "r0",
            "metadataMap": {"userId": "u1", "junk": "z"},
            "features": [
                {"name": "a", "term": "t", "value": 1.5},
                {"name": "a", "term": None, "value": 2},
                {"name": "b", "term": "t", "value": 3},
            ],
        },
        {
            "label": 0.25, "weight": None, "offset": None, "uid": None,
            "metadataMap": None,
            "features": [],
        },
    ]
    p = str(tmp_path / "legacy.avro")
    write_avro_file(p, schema, recs, codec="null")
    sh = {"global": FeatureShardConfig(("features",))}
    py, im = read_avro_dataset(
        p, sh, id_tag_columns=["userId"], engine="python"
    )
    nat, im_n = read_avro_dataset(
        p, sh, id_tag_columns=["userId"], engine="native"
    )
    assert sorted(im_n["global"].keys()) == sorted(im["global"].keys())
    _assert_dataset_equal(nat, py, sh)
    assert nat.labels[0] == 1.0 and nat.labels[1] == 0.25
    assert nat.weights[1] == 1.0 and nat.offsets[1] == 0.0  # null -> defaults
    assert nat.id_tags["userId"][0] == "u1"
    assert nat.id_tags["userId"][1] == ""


def test_native_column_remap(tmp_path):
    from photon_ml_tpu.io import InputColumnsNames

    schema = {
        "type": "record",
        "name": "Custom",
        "fields": [
            {"name": "target", "type": "double"},
            {"name": "importance", "type": "double"},
            {
                "name": "features",
                "type": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "FeatureAvro",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": "string"},
                            {"name": "value", "type": "double"},
                        ],
                    },
                },
            },
        ],
    }
    recs = [
        {"target": 1.0, "importance": 2.0,
         "features": [{"name": "f", "term": "", "value": 3.0}]}
    ]
    p = str(tmp_path / "custom.avro")
    write_avro_file(p, schema, recs)
    cols = InputColumnsNames.from_spec("response=target,weight=importance")
    sh = {"global": FeatureShardConfig(("features",))}
    kw = dict(response_column="target", columns=cols)
    py, _ = read_avro_dataset(p, sh, engine="python", **kw)
    nat, _ = read_avro_dataset(p, sh, engine="native", **kw)
    _assert_dataset_equal(nat, py, sh)
    assert nat.labels[0] == 1.0 and nat.weights[0] == 2.0


def test_native_reference_fixture_parity():
    heart = (
        "/root/reference/photon-client/src/integTest/resources/"
        "DriverIntegTest/input/heart.avro"
    )
    if not os.path.exists(heart):
        pytest.skip("reference fixture not mounted")
    sh = {"global": FeatureShardConfig(("features",))}
    py, im = read_avro_dataset(heart, sh, engine="python")
    nat, im_n = read_avro_dataset(heart, sh, engine="native")
    assert sorted(im_n["global"].keys()) == sorted(im["global"].keys())
    _assert_dataset_equal(nat, py, sh)


def test_native_engine_validation(game_avro):
    with pytest.raises(ValueError, match="unknown engine"):
        read_avro_dataset(game_avro, SHARDS, engine="bogus")
    reader = {"type": "record", "name": "X", "fields": []}
    with pytest.raises(ValueError, match="reader_schema"):
        read_avro_dataset(
            game_avro, SHARDS, engine="native", reader_schema=reader
        )


def test_native_corrupt_file_raises(tmp_path):
    p = str(tmp_path / "corrupt.avro")
    with open(p, "wb") as f:
        f.write(b"Obj\x01garbage-that-is-not-avro" + b"\x00" * 64)
    with pytest.raises(Exception):
        read_avro_dataset(
            p, {"global": FeatureShardConfig(("features",))}, engine="native"
        )


def test_native_response_remap_shadowed_by_stray_label(tmp_path):
    """An explicit response remap outranks a stray 'label' field in BOTH
    engines (the Python path's documented precedence)."""
    from photon_ml_tpu.io import InputColumnsNames

    schema = {
        "type": "record", "name": "R", "fields": [
            {"name": "label", "type": "double"},   # stray
            {"name": "y", "type": "double"},       # true response
            {"name": "features", "type": {"type": "array", "items": {
                "type": "record", "name": "FeatureAvro", "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"}]}}},
        ],
    }
    recs = [{"label": 9.0, "y": 1.0,
             "features": [{"name": "f", "term": "", "value": 1.0}]}]
    p = str(tmp_path / "shadow.avro")
    write_avro_file(p, schema, recs)
    cols = InputColumnsNames.from_spec("response=y")
    sh = {"global": FeatureShardConfig(("features",))}
    for engine in ("python", "native"):
        ds, _ = read_avro_dataset(p, sh, columns=cols, engine=engine)
        assert ds.labels[0] == 1.0, engine  # not the stray 9.0


def test_native_numeric_uid_and_tag(tmp_path):
    """Avro long uid (heart.avro-style union) formats with str(int) parity;
    an id tag naming a numeric field falls back to the Python codec under
    engine='auto' with identical results."""
    schema = {
        "type": "record", "name": "R", "fields": [
            {"name": "label", "type": "double"},
            {"name": "uid", "type": ["null", "string", "long", "int"]},
            {"name": "groupId", "type": "long"},
            {"name": "features", "type": {"type": "array", "items": {
                "type": "record", "name": "FeatureAvro", "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"}]}}},
        ],
    }
    recs = [
        {"label": 1.0, "uid": 42, "groupId": 7, "features": []},
        {"label": 0.0, "uid": "abc", "groupId": 8, "features": []},
        {"label": 0.0, "uid": None, "groupId": 9, "features": []},
    ]
    p = str(tmp_path / "numuid.avro")
    write_avro_file(p, schema, recs)
    sh = {"global": FeatureShardConfig(("features",))}
    py, _ = read_avro_dataset(p, sh, id_tag_columns=["groupId"], engine="python")
    auto, _ = read_avro_dataset(p, sh, id_tag_columns=["groupId"], engine="auto")
    _assert_dataset_equal(auto, py, sh)
    assert list(auto.uids) == ["42", "abc", None]
    assert list(auto.id_tags["groupId"]) == ["7", "8", "9"]


def test_native_randomized_schema_parity(tmp_path):
    """Fuzz: random record schemas (numeric/string unions, optional bags,
    maps, enums/fixed to skip, both codecs) must decode identically through
    both engines across many draws. engine='native' so a decoder crash or
    unsupported-shape fallback FAILS the test instead of silently comparing
    Python against itself."""
    import random

    rng = random.Random(20260730)

    def random_schema(case):
        fields = [{"name": "label", "type": rng.choice(
            ["double", ["int", "double"], ["double", "float", "int", "long", "boolean", "string"]])}]
        if rng.random() < 0.7:
            fields.append({"name": "weight", "type": rng.choice(
                ["float", ["null", "float"], ["null", "int", "double"]]), "default": None})
        if rng.random() < 0.7:
            fields.append({"name": "offset", "type": ["null", "long", "double"], "default": None})
        if rng.random() < 0.6:
            fields.append({"name": "uid", "type": ["null", "string", "long", "int"], "default": None})
        # a field the reader must skip, of annoying shape
        fields.append({"name": f"junk{case}", "type": rng.choice([
            {"type": "enum", "name": f"E{case}", "symbols": ["A", "B", "C"]},
            {"type": "fixed", "name": f"X{case}", "size": 5},
            {"type": "array", "items": ["null", "string", "double"]},
            {"type": "map", "values": ["null", "long"]},
            {"type": "record", "name": f"N{case}", "fields": [
                {"name": "a", "type": ["null", "string"]},
                {"name": "b", "type": "double"}]},
        ])})
        if rng.random() < 0.8:
            fields.append({"name": "metadataMap", "type": rng.choice([
                {"type": "map", "values": "string"},
                ["null", {"type": "map", "values": ["boolean", "long", "string"]}],
            ]), "default": None})
        term_type = rng.choice(["string", ["null", "string"]])
        value_type = rng.choice(["double", "float", ["int", "double"]])
        fields.append({"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "FeatureAvro", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": term_type},
                {"name": "value", "type": value_type}]}}})
        return {"type": "record", "name": f"Case{case}", "fields": fields}

    def random_value(t, case):
        if isinstance(t, list):
            return random_value(rng.choice(t), case)
        if isinstance(t, dict):
            tt = t["type"]
            if tt == "enum":
                return rng.choice(t["symbols"])
            if tt == "fixed":
                return bytes(rng.randrange(256) for _ in range(t["size"]))
            if tt == "array":
                return [random_value(t["items"], case) for _ in range(rng.randrange(3))]
            if tt == "map":
                return {f"k{i}": random_value(t["values"], case) for i in range(rng.randrange(3))}
            if tt == "record":
                return {f["name"]: random_value(f["type"], case) for f in t["fields"]}
        if t == "null":
            return None
        if t == "boolean":
            return rng.random() < 0.5
        if t in ("int", "long"):
            return rng.randrange(-1000, 1000)
        if t in ("float", "double"):
            return round(rng.uniform(-5, 5), 3)
        if t == "string":
            return rng.choice(["0.5", "x", "café", "", "-3"])  # some parse as numbers
        if t == "bytes":
            return b"bb"
        raise AssertionError(t)

    n_mismatch = 0
    for case in range(20):
        schema = random_schema(case)
        by_name = {f["name"]: f["type"] for f in schema["fields"]}
        recs = []
        for i in range(rng.randrange(1, 40)):
            rec = {}
            for f in schema["fields"]:
                if f["name"] == "label":
                    # labels must be numeric-parseable for _num parity
                    t = f["type"]
                    while True:
                        v = random_value(t, case)
                        try:
                            float(v)
                            break
                        except (TypeError, ValueError):
                            continue
                    rec["label"] = v
                elif f["name"] == "features":
                    rec["features"] = [
                        {"name": f"f{rng.randrange(6)}",
                         "term": random_value(by_name["features"]["items"]["fields"][1]["type"], case),
                         "value": random_value(by_name["features"]["items"]["fields"][2]["type"], case)}
                        for _ in range(rng.randrange(5))
                    ]
                else:
                    rec[f["name"]] = random_value(f["type"], case)
            recs.append(rec)
        p = str(tmp_path / f"fuzz{case}.avro")
        write_avro_file(p, schema, recs, codec=rng.choice(["null", "deflate"]))
        sh = {"global": FeatureShardConfig(("features",))}
        kw = dict(id_tag_columns=["k0"])
        py, im = read_avro_dataset(p, sh, engine="python", **kw)
        nat, im_n = read_avro_dataset(p, sh, engine="native", **kw)
        try:
            assert sorted(im_n["global"].keys()) == sorted(im["global"].keys())
            _assert_dataset_equal(nat, py, sh)
        except AssertionError as e:
            n_mismatch += 1
            print(f"case {case} mismatch: {e}\nschema: {schema}")
    assert n_mismatch == 0


def test_native_nan_values_match_python_engine(tmp_path):
    """Present-but-NaN label/offset/weight must decode identically on both
    engines: the native decoder reports field PRESENCE explicitly, so a
    genuine NaN propagates instead of collapsing to the absent-field default
    (round-3 advisor finding)."""
    from photon_ml_tpu.io import FeatureShardConfig, read_avro_dataset, write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO

    nan = float("nan")
    recs = [
        {"label": nan, "features": [{"name": "a", "term": "", "value": 1.0}]},
        {"label": 1.0, "offset": nan,
         "features": [{"name": "a", "term": "", "value": 2.0}]},
        {"label": 0.0, "weight": nan,
         "features": [{"name": "a", "term": "", "value": 3.0}]},
        # absent numeric fields still get the defaults
        {"label": 1.0, "features": [{"name": "a", "term": "", "value": 4.0}]},
    ]
    p = str(tmp_path / "nan.avro")
    write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs)
    sh = {"g": FeatureShardConfig(("features",))}
    py, _ = read_avro_dataset(p, sh, engine="python")
    nat, _ = read_avro_dataset(p, sh, engine="native")
    np.testing.assert_array_equal(py.labels, nat.labels)
    np.testing.assert_array_equal(py.offsets, nat.offsets)
    np.testing.assert_array_equal(py.weights, nat.weights)
    assert np.isnan(nat.labels[0])
    assert np.isnan(nat.offsets[1])
    assert np.isnan(nat.weights[2])
    assert nat.offsets[3] == 0.0 and nat.weights[3] == 1.0


def test_chunked_parallel_decode_matches_single(tmp_path):
    """decode_file_chunks: a multi-block file decoded on a thread pool must
    reproduce the single-call decode exactly — including with row windows
    that start/stop mid-chunk (round-4 parallel-ingest path)."""
    from photon_ml_tpu import native
    from photon_ml_tpu.io import FeatureShardConfig, read_avro_dataset, write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO

    rng = np.random.default_rng(5)
    n = 2000
    recs = [
        {
            "label": float(rng.integers(0, 2)),
            "weight": float(rng.uniform(0.5, 2.0)),
            "features": [
                {"name": f"f{j}", "term": "", "value": float(rng.normal())}
                for j in rng.choice(8, size=rng.integers(1, 5), replace=False)
            ],
            "metadataMap": {"userId": f"u{rng.integers(0, 30)}"},
        }
        for _ in range(n)
    ]
    p = str(tmp_path / "blocks.avro")
    # small sync interval => many independent blocks to parallelize over
    write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs, sync_interval_records=100)

    num_fields = {"label": 0, "offset": 1, "weight": 2}
    str_fields = {"uid": 0}
    bag_fields = {"features": 0}
    map_keys = {"userId": 1}

    single = native.decode_file(p, num_fields, str_fields, bag_fields, map_keys)
    for row_range in [None, (0, n), (137, 1873), (500, 501), (1999, 2000)]:
        chunks = native.decode_file_chunks(
            p, num_fields, str_fields, bag_fields, map_keys,
            row_range=row_range, n_threads=4,
        )
        if row_range is None or row_range == (0, n):
            assert len(chunks) > 1, "multi-block file should split into chunks"
        total = sum(c.n_rows for c in chunks)
        lo, hi = row_range if row_range else (0, n)
        assert total == hi - lo
        # stitch numeric columns and compare to the single decode's window
        for s in range(3):
            got = np.concatenate([c.num_cols[s] for c in chunks])
            np.testing.assert_array_equal(got, single.num_cols[s][lo:hi])
            gotp = np.concatenate([c.num_present[s] for c in chunks])
            np.testing.assert_array_equal(gotp, single.num_present[s][lo:hi])
        # stitch bag triples with per-chunk row offsets
        offs = np.cumsum([0] + [c.n_rows for c in chunks])
        got_rows, got_keys, got_vals = [], [], []
        for ci, c in enumerate(chunks):
            rows, kid, vals, keys = c.bags[0]
            got_rows.append(rows + offs[ci])
            got_keys.extend(keys[k] for k in kid)
            got_vals.append(vals)
        rows_s, kid_s, vals_s, keys_s = single.bags[0]
        m = (rows_s >= lo) & (rows_s < hi)
        np.testing.assert_array_equal(np.concatenate(got_rows), rows_s[m] - lo)
        assert got_keys == [keys_s[k] for k in kid_s[m]]
        np.testing.assert_array_equal(np.concatenate(got_vals), vals_s[m])

    # end-to-end: the reader with chunked decode matches the python engine
    sh = {"g": FeatureShardConfig(("features",))}
    py, _ = read_avro_dataset(p, sh, id_tag_columns=["userId"], engine="python")
    nat, _ = read_avro_dataset(p, sh, id_tag_columns=["userId"], engine="native")
    np.testing.assert_array_equal(py.labels, nat.labels)
    np.testing.assert_array_equal(py.weights, nat.weights)
    assert list(py.id_tags["userId"]) == list(nat.id_tags["userId"])
    # COO entry order is engine-specific (both sort downstream): compare the
    # canonicalized triples
    pr, pc, pv = py.shard_coo["g"]
    nr, nc, nv = nat.shard_coo["g"]
    po, no = np.lexsort((pc, pr)), np.lexsort((nc, nr))
    np.testing.assert_array_equal(pr[po], nr[no])
    np.testing.assert_array_equal(pc[po], nc[no])
    np.testing.assert_allclose(pv[po], nv[no])
