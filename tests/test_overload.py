"""Overload-proof serving: coordinated-omission accounting, deadline-budget
admission control, hostile socket input, the TCP front, /healthz overload
signaling, and the slow-engine chaos drill.

The open-loop math tests pin WHY the load harness measures from intended
send time: the same FIFO server, measured closed-loop, hides a stall's
queueing delay inside think time (one bad sample), while the open-loop
accounting charges the backlog to every request scheduled during it.

The admission tests pin the shed contract: every refusal is a typed
:class:`ShedError` with a reason (``queue_full`` / ``deadline`` /
``expired``), every refusal is counted in
``photon_serving_shed_total{reason=}``, and no request is ever dropped
without a response — the invariant the chaos drill then holds under a
PHOTON_FAULTS slow-engine storm with a live snapshot flip in the middle.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from photon_ml_tpu import obs, serving
from photon_ml_tpu.robust import faults
from photon_ml_tpu.serving import loadgen
from photon_ml_tpu.serving.batcher import MicroBatcher, ShedError


@pytest.fixture
def run_telemetry():
    run = obs.RunTelemetry()
    with obs.use_run(run):
        yield run


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


def counter_total(run, name, **labels):
    total = 0.0
    for m in run.registry.snapshot():
        if m["name"] == name and m["kind"] == "counter":
            got = m.get("labels", {})
            if all(got.get(k) == v for k, v in labels.items()):
                total += m["value"]
    return total


class FakeEngine:
    """Jax-free stand-in for ScoreEngine: score = sum of feature values +
    offset, with optional per-batch delay/error/blocking hooks."""

    def __init__(self, delay_s=0.0, fail=False, block_on=None):
        self.delay_s = delay_s
        self.fail = fail
        self.block_on = block_on  # threading.Event the batch waits for
        self.scored = []

    def warm(self):
        pass

    def score_requests(self, requests):
        if self.block_on is not None:
            assert self.block_on.wait(10.0)
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise ValueError("injected engine failure")
        self.scored.extend(requests)
        return [
            float(
                sum(v for _, vals in r.features.items() for v in vals[1])
                + r.offset
            )
            for r in requests
        ]


def make_request(value=1.0, offset=0.0, deadline_ms=None):
    doc = {
        "features": {"globalShard": [[0], [value]]},
        "offset": offset,
    }
    if deadline_ms is not None:
        doc["deadline_ms"] = deadline_ms
    return doc


def score_request(value=1.0, offset=0.0):
    return serving.ScoreRequest(
        features={"globalShard": ((0,), (value,))}, offset=offset
    )


# -- coordinated-omission math ------------------------------------------------


def test_poisson_schedule_deterministic_and_calibrated():
    a = loadgen.poisson_intended_times(500.0, 4.0, seed=7)
    b = loadgen.poisson_intended_times(500.0, 4.0, seed=7)
    np.testing.assert_array_equal(a, b)
    c = loadgen.poisson_intended_times(500.0, 4.0, seed=8)
    assert not np.array_equal(a, c)
    assert a[0] >= 0 and a[-1] <= 4.0
    assert np.all(np.diff(a) >= 0)
    # ~500 qps x 4s = ~2000 arrivals; Poisson sd ~45, allow 5 sigma
    assert 1750 <= len(a) <= 2250
    with pytest.raises(ValueError):
        loadgen.poisson_intended_times(0.0, 1.0)
    with pytest.raises(ValueError):
        loadgen.poisson_intended_times(10.0, -1.0)


def test_open_loop_reports_queueing_a_closed_loop_hides():
    """One 1-second stall on a server drained every 10ms: the closed-loop
    client records ONE bad sample (its other latencies are pure service
    time), while the open-loop accounting charges the backlog to every
    request scheduled during the stall — the true experienced latency."""
    n = 101
    intended = [0.01 * k for k in range(n)]  # 100 qps, 1s worth
    service = [1.0] + [0.001] * (n - 1)  # first request stalls the server
    open_lat = loadgen.simulate_fifo_open_loop(intended, service)
    closed_lat = loadgen.simulate_fifo_closed_loop(service)
    # closed loop: only the stalled request itself looks slow
    assert np.median(closed_lat) == 0.001
    assert sum(l > 0.1 for l in closed_lat) == 1
    # open loop: most of the schedule queued behind the stall
    assert np.median(open_lat) > 0.25
    assert sum(l > 0.1 for l in open_lat) > n // 2
    # identical servers, identical work — the measurements differ only in
    # what they charge the queue to
    assert sum(open_lat) > 10 * sum(closed_lat)


def test_open_loop_matches_closed_loop_when_server_keeps_up():
    intended = [0.01 * k for k in range(50)]
    service = [0.002] * 50  # server always free by the next arrival
    np.testing.assert_allclose(
        loadgen.simulate_fifo_open_loop(intended, service),
        loadgen.simulate_fifo_closed_loop(service),
        atol=1e-12,
    )


def test_find_knee_highest_served_step():
    def step(offered, served):
        return loadgen.OpenLoopResult(
            offered_qps=offered, duration_s=1.0, sent=int(offered),
            completed=int(served), shed_admission={}, shed_expired=0,
            errors=0, served_qps=served, achieved_offered_qps=offered,
            latency_mean_s=0.0, latency_p50_s=0.0, latency_p99_s=0.0,
        )

    steps = [step(100, 99), step(200, 195), step(400, 250), step(800, 260)]
    assert loadgen.find_knee(steps).offered_qps == 200
    assert loadgen.find_knee([step(400, 250)]) is None


# -- deadline / shed semantics -----------------------------------------------


def test_queue_full_sheds_with_counted_refusal(run_telemetry):
    gate = threading.Event()
    eng = FakeEngine(block_on=gate)
    b = MicroBatcher(lambda: eng, max_batch=1, max_latency_ms=0.1, max_pending=2)
    try:
        admitted = [b.submit(score_request()) for _ in range(2)]
        with pytest.raises(ShedError) as exc:
            b.submit(score_request())
        assert exc.value.reason == "queue_full"
        gate.set()
        for f in admitted:
            assert isinstance(f.result(timeout=10.0), float)
        assert counter_total(
            run_telemetry, "photon_serving_shed_total", reason="queue_full"
        ) == 1
        # offered counts admitted AND shed
        assert counter_total(run_telemetry, "photon_serving_offered_total") == 3
    finally:
        gate.set()
        b.close()


def test_deadline_admission_sheds_from_ewma(run_telemetry):
    eng = FakeEngine(delay_s=0.2)
    b = MicroBatcher(lambda: eng, max_batch=4, max_latency_ms=0.1)
    try:
        # prime the service-rate EWMA with one genuinely slow batch
        b.submit(score_request()).result(timeout=10.0)
        assert b.queue_stats()["ewma_service_seconds"] > 0.1
        with pytest.raises(ShedError) as exc:
            b.submit(score_request(), deadline_s=0.01)
        assert exc.value.reason == "deadline"
        assert "deadline budget" in str(exc.value)
        assert counter_total(
            run_telemetry, "photon_serving_shed_total", reason="deadline"
        ) == 1
        # a request with headroom is still admitted
        assert isinstance(
            b.submit(score_request(), deadline_s=5.0).result(timeout=10.0),
            float,
        )
    finally:
        b.close()


def test_expired_in_queue_shed_before_scoring_never_dropped(run_telemetry):
    gate = threading.Event()
    eng = FakeEngine(block_on=gate)
    b = MicroBatcher(lambda: eng, max_batch=1, max_latency_ms=0.1)
    try:
        blocker = b.submit(score_request())  # occupies the engine
        time.sleep(0.05)  # let the worker pick it up into its own batch
        doomed = b.submit(score_request(offset=42.0), deadline_s=0.02)
        time.sleep(0.05)  # deadline passes while queued
        gate.set()
        assert isinstance(blocker.result(timeout=10.0), float)
        with pytest.raises(ShedError) as exc:
            doomed.result(timeout=10.0)
        assert exc.value.reason == "expired"
        assert counter_total(
            run_telemetry, "photon_serving_shed_total", reason="expired"
        ) == 1
        # shed BEFORE scoring: the engine never saw the expired request
        assert all(r.offset != 42.0 for r in eng.scored)
    finally:
        gate.set()
        b.close()


def test_engine_failure_propagates_counted_not_shed(run_telemetry):
    eng = FakeEngine(fail=True)
    b = MicroBatcher(lambda: eng, max_batch=4, max_latency_ms=0.5)
    try:
        futs = [b.submit(score_request()) for _ in range(2)]
        for f in futs:
            with pytest.raises(ValueError, match="injected engine failure"):
                f.result(timeout=10.0)
        assert counter_total(
            run_telemetry, "photon_serving_request_errors_total"
        ) == 2
        assert counter_total(run_telemetry, "photon_serving_shed_total") == 0
    finally:
        b.close()


def test_queue_gauges_track_admission_state(run_telemetry):
    eng = FakeEngine(delay_s=0.05)
    b = MicroBatcher(lambda: eng, max_batch=8, max_latency_ms=0.5)
    try:
        b.submit(score_request()).result(timeout=10.0)
        stats = b.queue_stats()
        assert stats["pending"] == 0
        assert stats["ewma_service_seconds"] > 0.01
        snap = {
            m["name"]: m["value"]
            for m in run_telemetry.registry.snapshot()
            if m["kind"] == "gauge"
        }
        assert snap["photon_serving_queue_depth"] == 0
        assert "photon_serving_drain_estimate_seconds" in snap
    finally:
        b.close()


def test_open_loop_against_live_batcher_accounts_every_request(run_telemetry):
    eng = FakeEngine(delay_s=0.002)
    b = MicroBatcher(lambda: eng, max_batch=64, max_latency_ms=1.0, max_pending=16)
    try:
        res = loadgen.run_open_loop(
            b.submit,
            [score_request()],
            offered_qps=300.0,
            duration_s=0.5,
            seed=3,
            deadline_s=0.05,
        )
        assert res.sent > 0
        assert res.sent == res.completed + res.shed_total + res.errors
        counted = counter_total(run_telemetry, "photon_serving_offered_total")
        assert counted == res.sent
        assert counter_total(run_telemetry, "photon_serving_shed_total") == (
            res.shed_total
        )
        # the bounded-p99 guarantee for ADMITTED requests: queue wait fits
        # the deadline budget, so intended-send-time p99 stays within the
        # budget plus a service/scheduling margin
        assert res.latency_p99_s <= 0.15
    finally:
        b.close()


# -- the socket front: TCP + hostile input -----------------------------------


@pytest.fixture
def tcp_server(run_telemetry):
    """ScoringServer over a fake engine on an ephemeral TCP port."""
    server = serving.ScoringServer(engine=FakeEngine(), max_latency_ms=0.5)
    stop = threading.Event()
    bound = {}
    ready = threading.Event()

    def on_bound(addr):
        bound["addr"] = addr
        ready.set()

    t = threading.Thread(
        target=serving.serve_socket,
        kwargs=dict(
            server=server, listen="127.0.0.1:0", stop_event=stop,
            on_bound=on_bound,
        ),
    )
    t.start()
    assert ready.wait(10.0)
    yield server, bound["addr"], run_telemetry
    stop.set()
    t.join(timeout=10.0)
    assert not t.is_alive(), "serve_socket leaked its listener thread"
    server.close()


def _connect(addr):
    conn = socket.create_connection(tuple(addr))
    return conn, conn.makefile("rwb")


def _roundtrip(f, doc):
    f.write((json.dumps(doc) + "\n").encode())
    f.flush()
    out = json.loads(f.readline())
    # every response shape carries a request-scoped trace id and echoes the
    # resolved model (the fixture serves one unnamed store, so everything
    # lands on the "default" bulkhead); strip both so the exact-dict asserts
    # below keep pinning the rest of the protocol
    assert out.pop("trace_id"), out
    assert out.pop("model") == "default", out
    return out


def test_tcp_roundtrip_shared_handler(tcp_server):
    _, addr, _ = tcp_server
    conn, f = _connect(addr)
    try:
        out = _roundtrip(f, make_request(value=2.5, offset=1.0))
        assert out == {"score": 3.5}
        # deadline_ms rides along per request
        out = _roundtrip(f, make_request(value=1.0, deadline_ms=5000))
        assert out == {"score": 1.0}
    finally:
        conn.close()


def test_hostile_inputs_typed_errors_and_counters(tcp_server):
    _, addr, run = tcp_server
    conn, f = _connect(addr)
    try:
        cases = [
            (b"this is not json\n", "not_json"),
            (b"[1, 2, 3]\n", "bad_fields"),  # JSON, but not an object
            (b'{"ids": {}}\n', "bad_fields"),  # missing features
            (b'{"features": "nonsense"}\n', "bad_fields"),
            (b'{"features": {"g": [[0], [1.0, 2.0]]}}\n', "bad_fields"),
            (b'{"features": {"g": [[-1], [1.0]]}}\n', "bad_fields"),
            (b'{"features": {"g": [[0], ["x"]]}}\n', "bad_fields"),
            (b'{"features": {"g": [[0], [1.0]]}, "deadline_ms": 0}\n',
             "bad_fields"),
            (b'{"features": {"g": [[0], [1.0]]}, "offset": "z"}\n',
             "bad_fields"),
        ]
        for line, kind in cases:
            f.write(line)
            f.flush()
            out = json.loads(f.readline())
            assert out["error_type"] == "bad_request", (line, out)
            assert out["kind"] == kind, (line, out)
        # the connection survived every malformed line
        assert _roundtrip(f, make_request(value=1.5)) == {"score": 1.5}
        assert counter_total(
            run, "photon_serving_bad_request_total", kind="not_json"
        ) == 1
        assert counter_total(
            run, "photon_serving_bad_request_total", kind="bad_fields"
        ) == 8
    finally:
        conn.close()


def test_oversized_line_refused_and_connection_closed(tcp_server):
    _, addr, run = tcp_server
    conn, f = _connect(addr)
    try:
        pad = b"x" * (serving.MAX_REQUEST_LINE_BYTES + 16)
        f.write(b'{"pad": "' + pad + b'"}\n')
        f.flush()
        out = json.loads(f.readline())
        assert out["error_type"] == "bad_request" and out["kind"] == "oversized"
        # framing is unrecoverable: the server closes after responding
        assert f.readline() == b""
        assert counter_total(
            run, "photon_serving_bad_request_total", kind="oversized"
        ) == 1
    finally:
        conn.close()


def test_mid_line_disconnect_counted_clean_close(tcp_server):
    _, addr, run = tcp_server
    conn = socket.create_connection(tuple(addr))
    conn.sendall(b'{"features": {"g": ')  # no newline, then vanish
    conn.close()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if counter_total(
            run, "photon_serving_bad_request_total", kind="disconnect"
        ):
            break
        time.sleep(0.01)
    assert counter_total(
        run, "photon_serving_bad_request_total", kind="disconnect"
    ) == 1


def test_shed_surfaces_as_typed_socket_error(run_telemetry):
    eng = FakeEngine(delay_s=0.2)
    server = serving.ScoringServer(
        engine=eng, max_batch=4, max_latency_ms=0.1
    )
    stop = threading.Event()
    bound = {}
    ready = threading.Event()
    t = threading.Thread(
        target=serving.serve_socket,
        kwargs=dict(
            server=server, listen="127.0.0.1:0", stop_event=stop,
            on_bound=lambda a: (bound.update(addr=a), ready.set()),
        ),
    )
    t.start()
    assert ready.wait(10.0)
    try:
        conn, f = _connect(bound["addr"])
        try:
            # prime the EWMA so the admission estimate knows batches are slow
            assert "score" in _roundtrip(f, make_request())
            out = _roundtrip(f, make_request(deadline_ms=1))
            assert out["error_type"] == "shed"
            assert out["reason"] == "deadline"
            assert counter_total(
                run_telemetry, "photon_serving_shed_total", reason="deadline"
            ) == 1
            # connection still serves admitted requests
            assert "score" in _roundtrip(f, make_request(deadline_ms=60_000))
        finally:
            conn.close()
    finally:
        stop.set()
        t.join(timeout=10.0)
        server.close()


def test_af_unix_front_unchanged(run_telemetry, tmp_path):
    server = serving.ScoringServer(engine=FakeEngine(), max_latency_ms=0.5)
    stop = threading.Event()
    sock_path = str(tmp_path / "serve.sock")
    ready = threading.Event()
    t = threading.Thread(
        target=serving.serve_socket,
        kwargs=dict(
            server=server, path=sock_path, stop_event=stop,
            on_bound=lambda a: ready.set(),
        ),
    )
    t.start()
    assert ready.wait(10.0)
    try:
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.connect(sock_path)
        f = c.makefile("rwb")
        assert _roundtrip(f, make_request(value=2.0)) == {"score": 2.0}
        c.close()
    finally:
        stop.set()
        t.join(timeout=10.0)
        server.close()
    assert not os.path.exists(sock_path)


def test_serve_socket_needs_exactly_one_front(run_telemetry, tmp_path):
    server = serving.ScoringServer(engine=FakeEngine())
    try:
        with pytest.raises(ValueError, match="exactly one of path"):
            serving.serve_socket(server)
        with pytest.raises(ValueError, match="exactly one of path"):
            serving.serve_socket(
                server, path=str(tmp_path / "s.sock"), listen="127.0.0.1:0"
            )
    finally:
        server.close()


def test_stop_event_closes_open_connections_deterministically(tcp_server):
    _, addr, _ = tcp_server
    # a connection sitting in a blocked read must be shut down at stop time
    conn = socket.create_connection(tuple(addr))
    try:
        before = threading.active_count()
        # the fixture teardown sets stop + joins; this test just proves the
        # blocked connection doesn't survive it
        time.sleep(0.05)
        assert threading.active_count() >= before  # handler thread is live
    finally:
        conn.close()


# -- /healthz overload + /statusz admission ----------------------------------


def test_healthz_overloaded_while_shed_rate_exceeds_threshold():
    import urllib.error
    import urllib.request

    run = obs.RunTelemetry()
    run.status.update(overload_shed_threshold=5.0)
    shed = run.registry.counter(
        "photon_serving_shed_total", "test"
    ).labels(reason="deadline")
    srv = obs.IntrospectionServer(run, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                return r.status, json.loads(r.read())

        assert get("/healthz") == (200, {"status": "ok"})  # first sample
        shed.inc(1000)  # a storm between scrapes
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read()) == {"status": "overloaded"}
        time.sleep(0.05)  # no new sheds: rate decays below threshold
        assert get("/healthz") == (200, {"status": "ok"})
    finally:
        srv.stop()


def test_statusz_offered_served_shed_and_admission():
    run = obs.RunTelemetry()
    reg = run.registry
    reg.counter("photon_serving_requests_total", "t").inc(90)
    reg.counter("photon_serving_offered_total", "t").inc(100)
    reg.counter("photon_serving_shed_total", "t").labels(reason="deadline").inc(7)
    reg.counter("photon_serving_shed_total", "t").labels(reason="queue_full").inc(3)
    reg.counter("photon_serving_bad_request_total", "t").labels(kind="not_json").inc(2)
    reg.gauge("photon_serving_queue_depth", "t").set(4)
    reg.gauge("photon_serving_drain_estimate_seconds", "t").set(0.25)
    srv = obs.IntrospectionServer(run, port=0)
    try:
        doc = srv.statusz()
        s = doc["serving"]
        assert s["requests_total"] == 90
        assert s["offered_total"] == 100
        assert s["shed_total"] == 10
        assert s["shed_by_reason"] == {"deadline": 7, "queue_full": 3}
        assert s["bad_requests"] == {"not_json": 2}
        assert s["admission"] == {
            "queue_depth": 4,
            "drain_estimate_seconds": 0.25,
        }
        assert "qps" not in s  # first scrape: no delta yet
        reg.counter("photon_serving_requests_total", "t").inc(10)
        reg.counter("photon_serving_offered_total", "t").inc(20)
        reg.counter("photon_serving_shed_total", "t").labels(
            reason="deadline"
        ).inc(10)
        time.sleep(0.05)
        s2 = srv.statusz()["serving"]
        assert s2["qps"] > 0
        assert s2["offered_qps"] > s2["qps"]
        assert s2["shed_qps"] > 0
    finally:
        srv.stop()


# -- fault sites + the chaos drill -------------------------------------------


def test_serving_score_delay_site_stalls_batch(run_telemetry):
    faults.configure("serving.score:delay120:1")
    eng = FakeEngine()
    b = MicroBatcher(lambda: eng, max_batch=4, max_latency_ms=0.1)
    try:
        t0 = time.perf_counter()
        b.submit(score_request()).result(timeout=10.0)
        assert time.perf_counter() - t0 >= 0.12
        assert counter_total(
            run_telemetry, "photon_faults_injected_total", site="serving.score"
        ) == 1
        # second batch: spec exhausted, fast again
        t0 = time.perf_counter()
        b.submit(score_request()).result(timeout=10.0)
        assert time.perf_counter() - t0 < 0.1
    finally:
        b.close()


def test_serving_score_io_site_is_counted_engine_error(run_telemetry):
    faults.configure("serving.score:io:1")
    eng = FakeEngine()
    b = MicroBatcher(lambda: eng, max_batch=4, max_latency_ms=0.1)
    try:
        with pytest.raises(faults.InjectedIOError):
            b.submit(score_request()).result(timeout=10.0)
        assert counter_total(
            run_telemetry, "photon_serving_request_errors_total"
        ) == 1
        # the engine never saw the batch; the next one scores clean
        assert eng.scored == []
        assert isinstance(b.submit(score_request()).result(timeout=10.0), float)
    finally:
        b.close()


# the chaos drill proper needs the real jax engine + snapshot publication
D_FIXED = 6


def _make_game_model(fe_shift=0.0, seed=0):
    import jax.numpy as jnp

    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import Coefficients, LogisticRegressionModel

    rng = np.random.default_rng(seed)
    fe = FixedEffectModel(
        model=LogisticRegressionModel(
            Coefficients(jnp.asarray(rng.standard_normal(D_FIXED) + fe_shift))
        ),
        feature_shard="globalShard",
    )
    return GameModel(models={"global": fe}, task="logistic_regression")


def _drill_request(rng):
    idx = np.sort(rng.choice(D_FIXED, size=4, replace=False))
    return serving.ScoreRequest(
        features={
            "globalShard": (
                tuple(int(i) for i in idx),
                tuple(rng.standard_normal(4).tolist()),
            )
        },
        offset=float(rng.standard_normal()),
    )


def test_chaos_drill_slow_engine_storm_with_live_flip(run_telemetry, tmp_path):
    """The acceptance drill: a seeded serving.score delay storm under live
    open-loop load with a snapshot publish + flip mid-storm. Invariants:
    zero requests without a response, every refusal counted, the flip lands
    cleanly, and the server scores correctly afterwards."""
    root = str(tmp_path / "serving")
    serving.publish_snapshot(root, "v1", game_model=_make_game_model(0.0))
    server = serving.ScoringServer(
        serving_root=root, max_batch=32, max_latency_ms=1.0, max_pending=64
    )
    try:
        rng = np.random.default_rng(0)
        requests = [_drill_request(rng) for _ in range(64)]
        server.submit(requests[0], deadline_s=60.0).result(timeout=60.0)

        # every other batch stalls 80ms: a degraded accelerator, not a
        # broken one — admission must shed what can't make its deadline
        faults.configure("serving.score:delay80:p0.5", seed=4)
        flip_err = []

        def flip_mid_storm():
            try:
                time.sleep(0.4)
                serving.publish_snapshot(
                    root, "v2", game_model=_make_game_model(2.0)
                )
                server.poke_refresh()
            except Exception as exc:  # pragma: no cover - surfaced below
                flip_err.append(exc)

        flipper = threading.Thread(target=flip_mid_storm)
        flipper.start()
        res = loadgen.run_open_loop(
            server.submit,
            requests,
            offered_qps=120.0,
            duration_s=1.5,
            seed=11,
            deadline_s=0.06,
        )
        flipper.join(timeout=30.0)
        faults.clear()

        assert not flip_err, flip_err
        # no request lost without a response
        assert res.sent == res.completed + res.shed_total + res.errors
        assert res.errors == 0  # delays degrade, they don't break
        # the storm actually fired and the controller actually shed
        assert counter_total(
            run_telemetry, "photon_faults_injected_total", site="serving.score"
        ) > 0
        assert res.shed_total > 0
        assert counter_total(
            run_telemetry, "photon_serving_shed_total"
        ) >= res.shed_total
        # the flip landed cleanly mid-storm
        assert server.snapshot_name == "v2"
        assert counter_total(
            run_telemetry, "photon_serving_refresh_total"
        ) == 1
        # and the post-storm server scores on the NEW coefficients
        req = requests[0]
        got = server.score(req, deadline_s=None)
        model = _make_game_model(2.0)
        w = np.asarray(model.models["global"].model.coefficients.means)
        gi, gv = req.features["globalShard"]
        want = req.offset + float(np.dot(w[np.asarray(gi)], np.asarray(gv)))
        assert got == pytest.approx(want, rel=1e-5)
    finally:
        faults.clear()
        server.close()


def test_serving_refresh_fault_swallowed_then_recovers(run_telemetry, tmp_path):
    root = str(tmp_path / "serving")
    serving.publish_snapshot(root, "v1", game_model=_make_game_model(0.0))
    server = serving.ScoringServer(serving_root=root, max_latency_ms=0.5)
    try:
        serving.publish_snapshot(root, "v2", game_model=_make_game_model(1.0))
        faults.configure("serving.refresh:io:1")
        server.poke_refresh()  # injected IO error: swallowed, still on v1
        assert server.snapshot_name == "v1"
        assert counter_total(
            run_telemetry,
            "photon_swallowed_errors_total",
        ) >= 1
        server.poke_refresh()  # spec exhausted: the retry flips
        assert server.snapshot_name == "v2"
    finally:
        server.close()


def test_faults_delay_grammar():
    (spec,) = faults.parse_faults("serving.score:delay:1")
    assert spec.kind == "delay" and spec.delay_s == pytest.approx(0.05)
    (spec,) = faults.parse_faults("serving.score:delay250:2x3")
    assert spec.kind == "delay" and spec.delay_s == pytest.approx(0.25)
    assert spec.at == 2 and spec.times == 3
    with pytest.raises(ValueError):
        faults.parse_faults("site:delayXX:1")
    with pytest.raises(ValueError, match="io|kill|nan|delay"):
        faults.parse_faults("site:bogus:1")
