"""Multi-model serving fleet (serving/fleet.py + serving/front.py): per-model
bulkheads, staggered refresh, and the replica front's failover drill.

The two chaos claims this file pins:

- **Bulkhead isolation** — a ``serving.score.<model>`` delay storm keyed to
  one resident model sheds (and counts) against that model alone; a victim
  model sharing the process completes every request with untouched latency.
- **Zero requests lost without a response** — kill a replica under open-loop
  load through the least-loaded front and every dispatched request still
  resolves: scored on a survivor (same trace_id, idempotent resubmit) or
  refused with a typed shed. ``sent == completed + shed + errors`` with
  ``errors == 0``.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import obs, serving
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, LogisticRegressionModel
from photon_ml_tpu.obs.http import compose_statusz
from photon_ml_tpu.plan import PlanError
from photon_ml_tpu.robust import faults
from photon_ml_tpu.serving.fleet import ModelSet
from photon_ml_tpu.serving.front import LeastLoadedFront

D_FIXED = 6
D_RE = 4


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


@pytest.fixture
def run_telemetry():
    run = obs.RunTelemetry()
    with obs.use_run(run):
        yield run


def counter_total(run, name, **labels):
    total = 0.0
    for m in run.registry.snapshot():
        if m["name"] == name and m["kind"] == "counter":
            got = m.get("labels", {})
            if all(got.get(k) == v for k, v in labels.items()):
                total += m["value"]
    return total


class FakeEngine:
    """Jax-free stand-in for ScoreEngine: score = value + offset, optional
    per-batch service delay (duck-typed into ModelSet like the real one)."""

    def __init__(self, value=0.0, delay_s=0.0):
        self.value = float(value)
        self.delay_s = delay_s
        self.batches = 0

    def warm(self):
        pass

    def score_requests(self, requests):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.batches += 1
        return [self.value + r.offset for r in requests]


def fake_request(model=None, offset=0.0):
    return serving.ScoreRequest(
        features={"g": ((0,), (1.0,))}, offset=offset, model=model
    )


def make_model(fe_shift=0.0, seed=0):
    """Small GLMix model with deterministic coefficients (store-backed
    tests; same shape for every ``fe_shift`` so ladders share compiles)."""
    rng = np.random.default_rng(seed)
    fe = FixedEffectModel(
        model=LogisticRegressionModel(
            Coefficients(jnp.asarray(rng.standard_normal(D_FIXED) + fe_shift))
        ),
        feature_shard="globalShard",
    )
    re = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=np.asarray(["uA", "uB", "uC"], dtype=object),
        coef_indices=jnp.asarray(
            [[0, 2, -1], [1, 3, -1], [0, 1, 2]], jnp.int32
        ),
        coef_values=jnp.asarray(rng.standard_normal((3, 3))),
    )
    return GameModel(
        models={"global": fe, "per-user": re}, task="logistic_regression"
    )


def store_request(rng, uid="uA", model=None):
    gidx = np.sort(rng.choice(D_FIXED, size=4, replace=False))
    return serving.ScoreRequest(
        features={
            "globalShard": (
                tuple(int(i) for i in gidx),
                tuple(rng.standard_normal(4).tolist()),
            ),
        },
        ids={"userId": uid},
        offset=float(rng.standard_normal()),
        model=model,
    )


# -- composition refusals ----------------------------------------------------


def test_duplicate_model_name_refused():
    with pytest.raises(PlanError, match="duplicate model name"):
        ModelSet([("a", FakeEngine()), ("a", FakeEngine())])


def test_front_refuses_af_unix_replicas():
    with pytest.raises(PlanError, match="not composable with AF_UNIX"):
        LeastLoadedFront(["/tmp/photon-serve.sock"])


def test_unknown_default_model_refused():
    with pytest.raises(ValueError, match="not in the fleet"):
        ModelSet({"a": FakeEngine()}, default_model="b")


# -- routing + bulkheads -----------------------------------------------------


def test_model_routing_and_unknown_model(run_telemetry):
    ms = ModelSet(
        {"alpha": FakeEngine(value=1.0), "beta": FakeEngine(value=100.0)},
        default_model="alpha",
        max_latency_ms=0.5,
    )
    try:
        assert ms.submit(fake_request()).result(5.0) == 1.0
        assert ms.submit(fake_request(model="beta")).result(5.0) == 100.0
        # the model= argument wins over the request's own field
        assert ms.submit(fake_request(model="beta"), model="alpha").result(
            5.0
        ) == 1.0
        with pytest.raises(serving.UnknownModelError) as exc:
            ms.resolve("gamma")
        assert exc.value.kind == "unknown_model"
        assert "this fleet holds ['alpha', 'beta']" in str(exc.value)
    finally:
        ms.close()


def test_ten_model_storm_isolates_to_one_bulkhead(run_telemetry):
    """The tentpole isolation drill: a delay storm keyed to ONE of ten
    resident models (the dynamic ``serving.score.<model>`` site) sheds that
    model past its deadline budget while a victim model in the same process
    completes every request at untouched latency."""
    engines = {f"m{i}": FakeEngine(value=i) for i in range(10)}
    ms = ModelSet(engines, max_latency_ms=0.5, max_pending=64)
    try:
        # every batch of m3 stalls 50ms; no other model's site fires
        faults.configure("serving.score.m3:delay50:p1", seed=1)
        results = serving.run_mixed_open_loop(
            ms.submit,
            {
                "storm": {
                    "requests": [fake_request(model="m3")],
                    "offered_qps": 120.0,
                    "deadline_s": 0.02,
                },
                "victim": {
                    "requests": [fake_request(model="m5")],
                    "offered_qps": 60.0,
                },
            },
            duration_s=1.0,
        )
        storm, victim = results["storm"], results["victim"]
        # accounting invariant per stream: nothing unaccounted for
        for r in (storm, victim):
            assert r.sent == (
                r.completed
                + sum(r.shed_admission.values())
                + r.shed_expired
                + r.errors
            )
        # the stormed model sheds (50ms batches vs a 20ms budget)...
        assert storm.shed_total > 0
        assert storm.errors == 0
        # ...while the victim never sheds, never errors, and never waits
        # behind the stormed model's batches
        assert victim.errors == 0
        assert victim.shed_total == 0
        assert victim.completed == victim.sent
        assert victim.latency_p99_s < 0.04
        # the refusals counted against the stormed bulkhead alone
        assert counter_total(
            run_telemetry, "photon_serving_shed_total", model="m3"
        ) == storm.shed_total
        assert counter_total(
            run_telemetry, "photon_serving_shed_total", model="m5"
        ) == 0
        # and the statusz per-model section tells the two models apart
        doc = compose_statusz(run_telemetry)
        models = doc["serving"]["models"]
        assert models["m3"]["shed_total"] > 0
        assert "shed_by_reason" not in models["m5"]
        assert models["m5"]["latency_p99_seconds"] < 0.04
    finally:
        ms.close()


# -- staggered refresh over real stores --------------------------------------


def test_staggered_refresh_flips_models_independently(
    run_telemetry, tmp_path
):
    rng = np.random.default_rng(0)
    root_a, root_b = str(tmp_path / "a"), str(tmp_path / "b")
    serving.publish_snapshot(root_a, "v1", game_model=make_model(0.0))
    serving.publish_snapshot(root_b, "v1", game_model=make_model(5.0))
    ms = ModelSet({"a": root_a, "b": root_b}, max_latency_ms=0.5)
    try:
        req = store_request(rng)
        s_a1 = ms.submit(req, model="a").result(10.0)
        s_b1 = ms.submit(req, model="b").result(10.0)
        assert ms.snapshot_names == {"a": "v1", "b": "v1"}

        # flip a alone: b's watcher never moves
        serving.publish_snapshot(root_a, "v2", game_model=make_model(2.0))
        ms.poke_refresh("a")
        assert ms.snapshot_names == {"a": "v2", "b": "v1"}
        assert ms.submit(req, model="a").result(10.0) != s_a1
        assert ms.submit(req, model="b").result(10.0) == s_b1

        # a torn publish on b (CURRENT names a snapshot that never landed)
        # is swallowed: b keeps serving v1, a keeps serving v2
        (tmp_path / "b" / "CURRENT").write_text("v9-missing\n")
        ms.poke_refresh("b")
        assert ms.snapshot_names == {"a": "v2", "b": "v1"}
        assert ms.submit(req, model="b").result(10.0) == s_b1
        assert counter_total(
            run_telemetry,
            "photon_swallowed_errors_total",
            site="serving.refresh",
        ) >= 1

        # the next good publish repairs b without touching a
        serving.publish_snapshot(root_b, "v2", game_model=make_model(7.0))
        ms.poke_refresh("b")
        assert ms.snapshot_names == {"a": "v2", "b": "v2"}
        assert ms.submit(req, model="b").result(10.0) != s_b1
        # per-model flip counts: a flipped once, b once
        assert counter_total(
            run_telemetry, "photon_serving_refresh_total", model="a"
        ) == 1
        assert counter_total(
            run_telemetry, "photon_serving_refresh_total", model="b"
        ) == 1
    finally:
        ms.close()


def test_same_shape_models_share_compiled_executables(run_telemetry, tmp_path):
    """The ladder executables are keyed by shape, not by model: warming the
    Nth same-shape model of a fleet compiles nothing new."""
    from photon_ml_tpu.serving.engine import ScoreEngine, _fe_score_ell

    sa = serving.build_store_from_model(make_model(0.0), str(tmp_path / "a"))
    sb = serving.build_store_from_model(make_model(3.0), str(tmp_path / "b"))
    e1 = ScoreEngine.from_store(serving.ModelStore.open(sa))
    e1.warm()
    cached = _fe_score_ell._cache_size()
    e2 = ScoreEngine.from_store(serving.ModelStore.open(sb))
    e2.warm()
    assert _fe_score_ell._cache_size() == cached


# -- the protocol's model field over a real socket ---------------------------


def _serve_tcp(server_like, serve_fn=None):
    """Start a TCP listener for ``server_like`` on an ephemeral port;
    returns (addr_str, stop_event, thread)."""
    stop = threading.Event()
    bound = {}
    ready = threading.Event()
    serve = serve_fn or serving.serve_socket
    t = threading.Thread(
        target=serve,
        kwargs=dict(
            listen="127.0.0.1:0",
            stop_event=stop,
            on_bound=lambda a: (bound.update(addr=a), ready.set()),
        ),
        args=(server_like,),
        daemon=True,
    )
    t.start()
    assert ready.wait(10.0)
    host, port = bound["addr"][:2]
    return f"{host}:{port}", stop, t


def _rpc(addr, doc):
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port))) as s, s.makefile(
        "rwb"
    ) as f:
        f.write((json.dumps(doc) + "\n").encode())
        f.flush()
        return json.loads(f.readline())


def test_model_echo_on_every_response_shape(run_telemetry):
    server = serving.ScoringServer(
        models={"alpha": FakeEngine(value=1.0), "beta": FakeEngine(value=2.0)},
        default_model="alpha",
        max_latency_ms=0.5,
    )
    addr, stop, t = _serve_tcp(server)
    try:
        doc = {"features": {"g": [[0], [1.0]]}, "offset": 0.5}
        ok = _rpc(addr, doc)
        assert (ok["score"], ok["model"]) == (1.5, "alpha")
        ok = _rpc(addr, {**doc, "model": "beta"})
        assert (ok["score"], ok["model"]) == (2.5, "beta")
        # unknown model: typed bad_request, counted, echoes the asked-for name
        bad = _rpc(addr, {**doc, "model": "gamma"})
        assert bad["error_type"] == "bad_request"
        assert bad["kind"] == "unknown_model"
        assert bad["model"] == "gamma"
        assert "this fleet holds" in bad["error"]
        assert counter_total(
            run_telemetry,
            "photon_serving_bad_request_total",
            kind="unknown_model",
        ) == 1
        # a non-string model field is a bad_fields refusal
        bad = _rpc(addr, {**doc, "model": 7})
        assert (bad["kind"], bad["model"]) == ("bad_fields", "alpha")
        # malformed JSON still answers (echoing the default model)
        host, port = addr.rsplit(":", 1)
        with socket.create_connection((host, int(port))) as s, s.makefile(
            "rwb"
        ) as f:
            f.write(b"not json\n")
            f.flush()
            out = json.loads(f.readline())
        assert (out["kind"], out["model"]) == ("not_json", "alpha")
    finally:
        stop.set()
        t.join(timeout=10.0)
        server.close()


# -- the replica front -------------------------------------------------------


def _two_replica_fleet(value=1.0):
    """Two single-model TCP replicas over fake engines + their stops."""
    servers, stops, threads, addrs = [], [], [], []
    for _ in range(2):
        srv = serving.ScoringServer(
            engine=FakeEngine(value=value), max_latency_ms=0.5
        )
        addr, stop, t = _serve_tcp(srv)
        servers.append(srv)
        stops.append(stop)
        threads.append(t)
        addrs.append(addr)
    return servers, stops, threads, addrs


def test_front_routes_least_loaded_and_fails_over(run_telemetry):
    """The replica-kill chaos drill: open-loop load through the front, one
    replica killed mid-run — zero requests end without a response."""
    servers, stops, threads, addrs = _two_replica_fleet()
    front = LeastLoadedFront(addrs, health_poll_seconds=0.1)
    try:
        assert front.score(fake_request(offset=0.25)) == 1.25
        reqs = [fake_request(offset=float(i)) for i in range(8)]
        holder = {}

        def drive():
            holder["r"] = serving.run_open_loop(
                front.submit, reqs, offered_qps=250.0, duration_s=1.2
            )

        dt = threading.Thread(target=drive)
        dt.start()
        time.sleep(0.4)
        stops[0].set()  # kill replica 0: conns shut down mid-request
        dt.join()
        r = holder["r"]
        assert r.sent == (
            r.completed
            + sum(r.shed_admission.values())
            + r.shed_expired
            + r.errors
        )
        assert r.errors == 0, "a request ended without a typed response"
        assert r.completed > 0
        # both replicas carried traffic before the kill; the survivor
        # carried everything after it
        routed = {
            a: counter_total(
                run_telemetry, "photon_serving_route_total", replica=a
            )
            for a in addrs
        }
        assert all(v > 0 for v in routed.values())
        states = front.replica_states()
        assert not states[addrs[0]]["up"]
        assert states[addrs[1]]["up"]
    finally:
        front.close()
        for stop in stops:
            stop.set()
        for t in threads:
            t.join(timeout=10.0)
        for srv in servers:
            srv.close()


def test_front_fault_sites_shed_typed(run_telemetry):
    """The two literal fleet fault sites: an injected ``serving.route``
    error sheds the request (typed, counted); an injected
    ``serving.replica`` error on a single-replica fleet exhausts the
    candidates into a typed ``no_replica`` shed — refusals, never drops."""
    servers, stops, threads, addrs = _two_replica_fleet()
    front = LeastLoadedFront(addrs[:1], health_poll_seconds=0.1)
    try:
        faults.configure("serving.route:io:1")
        with pytest.raises(serving.ShedError) as exc:
            front.score(fake_request())
        assert exc.value.reason == "route"
        faults.configure("serving.replica:io:1")
        with pytest.raises(serving.ShedError) as exc:
            front.score(fake_request())
        assert exc.value.reason == "no_replica"
        assert counter_total(
            run_telemetry, "photon_serving_front_sheds_total", reason="route"
        ) == 1
        assert counter_total(
            run_telemetry,
            "photon_serving_front_sheds_total",
            reason="no_replica",
        ) == 1
        faults.clear()
        # the maintenance thread reconnects the failed replica; the fleet
        # serves again without operator action
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                assert front.score(fake_request(offset=1.0)) == 2.0
                break
            except serving.ShedError:
                time.sleep(0.05)
        else:
            pytest.fail("replica never rejoined the rotation")
    finally:
        front.close()
        for stop in stops:
            stop.set()
        for t in threads:
            t.join(timeout=10.0)
        for srv in servers:
            srv.close()


def test_front_connection_pool_channels(run_telemetry):
    """``connections_per_replica`` opens K independent channels per replica
    (the JSON-lines protocol is serial per connection, so K is the front's
    concurrency into one replica). Channels show up as ``addr#k`` entries,
    score correctly, and spread concurrent load."""
    servers, stops, threads, addrs = _two_replica_fleet()
    front = LeastLoadedFront(
        addrs, health_poll_seconds=0.1, connections_per_replica=2
    )
    try:
        states = front.replica_states()
        expected = {addrs[0], f"{addrs[0]}#1", addrs[1], f"{addrs[1]}#1"}
        assert set(states) == expected
        assert front.score(fake_request(offset=0.25)) == 1.25
        reqs = [fake_request(offset=float(i)) for i in range(8)]
        r = serving.run_open_loop(
            front.submit, reqs, offered_qps=200.0, duration_s=0.6
        )
        assert r.errors == 0 and r.completed > 0
        routed = {
            a: counter_total(
                run_telemetry, "photon_serving_route_total", replica=a
            )
            for a in expected
        }
        # concurrent load reaches beyond one channel per replica
        assert sum(v > 0 for v in routed.values()) >= 3
        assert sum(routed.values()) >= r.completed
    finally:
        front.close()
        for stop in stops:
            stop.set()
        for t in threads:
            t.join(timeout=10.0)
        for srv in servers:
            srv.close()


def test_front_connection_pool_rejects_nonpositive():
    with pytest.raises(ValueError):
        LeastLoadedFront(["127.0.0.1:1"], connections_per_replica=0)


def test_front_socket_passthrough(run_telemetry):
    """``serve_front_socket``: clients speak the replica protocol to the
    front; responses (score, model echo, trace_id) relay back verbatim."""
    from photon_ml_tpu.serving.front import serve_front_socket

    servers, stops, threads, addrs = _two_replica_fleet(value=3.0)
    front = LeastLoadedFront(addrs, health_poll_seconds=0.1)
    faddr, fstop, ft = _serve_tcp(front, serve_fn=serve_front_socket)
    try:
        out = _rpc(faddr, {"features": {"g": [[0], [1.0]]}, "offset": 1.0})
        assert out["score"] == 4.0
        assert out["model"] == "default"
        assert out["trace_id"]
        out = _rpc(faddr, {"features": "nonsense"})
        assert out["error_type"] == "bad_request"
    finally:
        fstop.set()
        ft.join(timeout=10.0)
        front.close()
        for stop in stops:
            stop.set()
        for t in threads:
            t.join(timeout=10.0)
        for srv in servers:
            srv.close()
