"""Live introspection plane tests: Chrome-trace export schema, per-sweep
phase attribution, the /metrics + /healthz + /statusz endpoints (including a
concurrent scrape while spans are being emitted), and the bench.py --diff
regression gate."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.obs.timeline import SWEEP_SPAN_NAME, TimelineRecorder
from photon_ml_tpu.obs.tracing import Span, SpanEvent

# ---------------------------------------------------------------- helpers


def _mk_span(name, span_id, parent_id, start, dur, **attrs):
    """Hand-built span on a synthetic monotonic clock (start_perf). The
    clock is offset from zero: start_perf == 0.0 means "not stamped" and
    would fall back to start_unix."""
    return Span(
        name=name,
        span_id=span_id,
        parent_id=parent_id,
        start_unix=1_700_000_000.0 + start,
        attrs=dict(attrs),
        duration_s=dur,
        start_perf=100.0 + start,
    )


def _feed(recorder, spans):
    # children close before parents in real runs; feed in that order too
    for s in spans:
        recorder.handle(SpanEvent(span=s))


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


# ------------------------------------------------------------ chrome trace


def test_chrome_trace_schema_from_real_spans(tmp_path):
    """Golden schema: the export is valid Chrome-trace JSON — "X" complete
    events with microsecond ts/dur, pid/tid lane ids, span identity under
    args, "M" lane-name metadata, ts-sorted, displayTimeUnit set."""
    run = obs.RunTelemetry()
    rec = TimelineRecorder()
    run.register_listener(rec)
    with obs.use_run(run):
        with obs.span(SWEEP_SPAN_NAME, iteration=0):
            with obs.span("cd.coordinate", iteration=0, coordinate="global"):
                with obs.span("solve", phase="solve", coordinate="global"):
                    time.sleep(0.002)
            with obs.span("cd.eval", phase="eval"):
                pass

    doc = rec.chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    # round-trips through JSON (Perfetto ingests text)
    assert json.loads(json.dumps(doc)) == doc

    xs = [e for e in events if e["ph"] == "X"]
    ms = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in xs} == {
        SWEEP_SPAN_NAME, "cd.coordinate", "solve", "cd.eval"
    }
    for e in xs:
        assert e["cat"] == "photon"
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["dur"] >= 0
        assert "span_id" in e["args"] and "parent_id" in e["args"]
    # all spans ran on this thread -> one lane, named by the M events
    assert {e["tid"] for e in xs} == {threading.get_ident()}
    assert {m["name"] for m in ms} == {"process_name", "thread_name"}
    # ts-sorted and nesting is consistent: the sweep starts first
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    by_name = {e["name"]: e for e in xs}
    solve = by_name["solve"]
    assert solve["dur"] >= 2000  # slept 2ms, dur is in microseconds
    assert solve["args"]["phase"] == "solve"

    out = tmp_path / "trace.json"
    rec.write_chrome_trace(str(out))
    ondisk = json.load(open(out))
    assert ondisk["traceEvents"]


def test_chrome_trace_lane_ids_across_threads():
    run = obs.RunTelemetry()
    rec = TimelineRecorder()
    run.register_listener(rec)

    def worker():
        with obs.use_run(run):
            with obs.span("bg-work"):
                pass

    with obs.use_run(run):
        with obs.span("fg-work"):
            t = threading.Thread(target=worker, name="photon-test-worker")
            t.start()
            t.join()
    xs = {e["name"]: e for e in rec.chrome_trace()["traceEvents"] if e["ph"] == "X"}
    assert xs["fg-work"]["tid"] != xs["bg-work"]["tid"]
    lane_names = {
        m["args"]["name"]
        for m in rec.chrome_trace()["traceEvents"]
        if m["ph"] == "M" and m["name"] == "thread_name"
    }
    assert "photon-test-worker" in lane_names


# -------------------------------------------------------- phase attribution


def test_phase_attribution_serial_sweep_scores_zero_overlap():
    rec = TimelineRecorder()
    sweep = _mk_span(SWEEP_SPAN_NAME, "sw", None, 0.0, 10.0, iteration=0)
    _feed(
        rec,
        [
            _mk_span("solve", "a", "sw", 0.0, 4.0, phase="solve", coordinate="global"),
            _mk_span("score", "b", "sw", 4.0, 2.0, phase="score", coordinate="global"),
            _mk_span("eval", "c", "sw", 6.0, 1.0, phase="eval"),
            _mk_span("ckpt", "d", "sw", 7.0, 1.0, phase="checkpoint"),
            sweep,
        ],
    )
    att = rec.phase_attribution()
    assert att["n_sweeps"] == 1
    (rec0,) = att["sweeps"]
    assert rec0["iteration"] == 0
    assert rec0["wall_seconds"] == pytest.approx(10.0)
    assert rec0["phases"] == pytest.approx(
        {"solve": 4.0, "score": 2.0, "eval": 1.0, "checkpoint": 1.0}
    )
    assert rec0["coordinates"]["global"] == pytest.approx(
        {"solve": 4.0, "score": 2.0}
    )
    # serial: union == sum of phases, so overlap factor is exactly 0
    assert rec0["sum_of_phases_seconds"] == pytest.approx(8.0)
    assert rec0["critical_path_seconds"] == pytest.approx(8.0)
    assert rec0["overlap_factor"] == pytest.approx(0.0)
    # the attribution identity: critical path + unattributed == wall
    assert rec0["critical_path_seconds"] + rec0["other_seconds"] == pytest.approx(
        rec0["wall_seconds"]
    )
    assert att["total"]["overlap_factor"] == pytest.approx(0.0)


def test_phase_attribution_overlap_factor_rises_with_concurrency():
    rec = TimelineRecorder()
    _feed(
        rec,
        [
            _mk_span("solve", "a", "sw", 0.0, 4.0, phase="solve"),
            _mk_span("stage", "b", "sw", 2.0, 4.0, phase="stage"),
            _mk_span(SWEEP_SPAN_NAME, "sw", None, 0.0, 6.0, iteration=0),
        ],
    )
    (rec0,) = rec.phase_attribution()["sweeps"]
    # sum 8, union 6 -> 25% of phase time ran concurrently
    assert rec0["overlap_factor"] == pytest.approx(0.25)
    assert rec0["other_seconds"] == pytest.approx(0.0)


def test_phase_attribution_nested_phase_not_double_counted():
    """A phase span inside another phase span (fe_stream.stage dispatched
    from within the solve) is wall time its ancestor already owns — it must
    land in nested_phases, not inflate the overlap factor."""
    rec = TimelineRecorder()
    _feed(
        rec,
        [
            _mk_span("stage", "st", "so", 1.0, 2.0, phase="stage"),
            _mk_span("solve", "so", "sw", 0.0, 8.0, phase="solve"),
            _mk_span(SWEEP_SPAN_NAME, "sw", None, 0.0, 10.0, iteration=0),
        ],
    )
    (rec0,) = rec.phase_attribution()["sweeps"]
    assert rec0["phases"] == pytest.approx({"solve": 8.0})
    assert rec0["nested_phases"] == pytest.approx({"stage": 2.0})
    assert rec0["overlap_factor"] == pytest.approx(0.0)


def test_phase_attribution_clips_to_sweep_window():
    rec = TimelineRecorder()
    _feed(
        rec,
        [
            # starts before the sweep, ends inside: only [2, 5) attributes
            _mk_span("warm", "w", "sw", 0.0, 5.0, phase="solve"),
            # entirely outside the window: contributes nothing
            _mk_span("late", "l", "sw", 20.0, 1.0, phase="eval"),
            _mk_span(SWEEP_SPAN_NAME, "sw", None, 2.0, 6.0, iteration=0),
        ],
    )
    (rec0,) = rec.phase_attribution()["sweeps"]
    assert rec0["phases"] == pytest.approx({"solve": 3.0})
    assert "eval" not in rec0["phases"]


def test_phase_attribution_ignores_spans_of_other_sweeps():
    rec = TimelineRecorder()
    _feed(
        rec,
        [
            _mk_span("solve", "a0", "sw0", 0.0, 2.0, phase="solve"),
            _mk_span(SWEEP_SPAN_NAME, "sw0", None, 0.0, 3.0, iteration=0),
            _mk_span("solve", "a1", "sw1", 3.0, 4.0, phase="solve"),
            _mk_span(SWEEP_SPAN_NAME, "sw1", None, 3.0, 5.0, iteration=1),
        ],
    )
    att = rec.phase_attribution()
    assert att["n_sweeps"] == 2
    s0, s1 = att["sweeps"]
    assert s0["phases"] == pytest.approx({"solve": 2.0})
    assert s1["phases"] == pytest.approx({"solve": 4.0})
    assert att["total"]["wall_seconds"] == pytest.approx(8.0)
    assert att["total"]["phases"]["solve"] == pytest.approx(6.0)


# ------------------------------------------------------------ http endpoints


def test_endpoints_respond():
    run = obs.RunTelemetry()
    run.registry.counter("photon_test_total", "t").inc(3)
    run.status.update(sweep=1, coordinate="global")
    srv = obs.IntrospectionServer(run, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"

        status, ctype, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body) == {"status": "ok"}

        status, ctype, body = _get(base + "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        text = body.decode("utf-8")
        assert "# TYPE photon_test_total counter" in text
        assert "photon_test_total 3" in text

        status, ctype, body = _get(base + "/statusz")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["status"] == "ok"
        assert doc["sweep"] == 1 and doc["coordinate"] == "global"

        with pytest.raises(urllib.error.HTTPError) as e:
            _get(base + "/nope")
        assert e.value.code == 404
    finally:
        srv.stop()


def test_statusz_serving_section_and_qps():
    from photon_ml_tpu.serving.batcher import SERVING_LATENCY_BUCKETS

    run = obs.RunTelemetry()
    reg = run.registry
    reg.counter("photon_serving_requests_total", "").inc(100)
    lat = reg.histogram(
        "photon_serving_request_latency_seconds", "", buckets=SERVING_LATENCY_BUCKETS
    )
    for _ in range(10):
        lat.observe(0.002)
    srv = obs.IntrospectionServer(run, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        doc = json.loads(_get(base + "/statusz")[2])
        assert doc["serving"]["requests_total"] == 100
        assert 0.001 <= doc["serving"]["latency_p50_seconds"] <= 0.0025
        # first scrape has no previous sample -> no qps yet
        assert "qps" not in doc["serving"]
        reg.counter("photon_serving_requests_total", "").inc(50)
        time.sleep(0.05)
        doc2 = json.loads(_get(base + "/statusz")[2])
        assert doc2["serving"]["requests_total"] == 150
        assert doc2["serving"]["qps"] > 0
    finally:
        srv.stop()


def test_healthz_503_while_refresh_in_progress():
    """The serving snapshot-refresh flip raises refresh_in_progress on the
    StatusBoard; /healthz answers 503 for exactly that window so a load
    balancer drains the replica mid-publish."""
    run = obs.RunTelemetry()
    srv = obs.IntrospectionServer(run, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        run.status.update(refresh_in_progress=True)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(base + "/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read()) == {"status": "refreshing"}
        run.status.update(refresh_in_progress=False)
        status, _, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body) == {"status": "ok"}
    finally:
        srv.stop()


def test_statusz_memory_section():
    """/statusz carries live host RSS plus recorded device watermarks and
    hbm.budget headroom when the run sampled/streamed any."""
    run = obs.RunTelemetry()
    reg = run.registry
    obs.sample_memory(reg)
    reg.gauge("photon_mem_device_peak_bytes_in_use", "").labels(
        device="0"
    ).set(4096)
    reg.gauge("photon_stream_budget_bytes", "").labels(site="fe.train").set(
        2048
    )
    reg.gauge("photon_stream_budget_headroom_bytes", "").labels(
        site="fe.train"
    ).set(1024)
    srv = obs.IntrospectionServer(run, port=0)
    try:
        doc = json.loads(_get(f"http://127.0.0.1:{srv.port}/statusz")[2])
        mem = doc["memory"]
        assert mem["host"]["rss_bytes"] > 0  # live reading, not the sample
        assert mem["devices"]["0"]["peak_bytes_in_use"] == 4096
        assert mem["streaming"]["fe.train"]["hbm_budget_bytes"] == 2048
        assert mem["streaming"]["fe.train"]["hbm_budget_headroom_bytes"] == 1024
    finally:
        srv.stop()


def test_concurrent_scrape_during_span_storm():
    """Scrapes while another thread hammers spans + status updates: every
    response is complete, parseable, and never deadlocks the emitting
    thread."""
    run = obs.RunTelemetry()
    rec = TimelineRecorder()
    run.register_listener(rec)
    stop = threading.Event()

    def storm():
        with obs.use_run(run):
            i = 0
            while not stop.is_set():
                run.status.update(sweep=i, coordinate=f"c{i % 3}")
                with obs.span(SWEEP_SPAN_NAME, iteration=i):
                    with obs.span("solve", phase="solve", coordinate=f"c{i % 3}"):
                        run.registry.counter("photon_storm_total", "").inc()
                i += 1

    t = threading.Thread(target=storm, name="span-storm")
    t.start()
    srv = obs.IntrospectionServer(run, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        deadline = time.monotonic() + 10
        seen_sweeps = set()
        while time.monotonic() < deadline and len(seen_sweeps) < 3:
            doc = json.loads(_get(base + "/statusz")[2])
            assert doc["status"] == "ok"
            if "sweep" in doc:
                seen_sweeps.add(doc["sweep"])
            text = _get(base + "/metrics")[2].decode("utf-8")
            # exposition is complete: TYPE line present for emitted counters
            if "photon_storm_total" in text:
                assert "# TYPE photon_storm_total counter" in text
        assert len(seen_sweeps) >= 3  # observed live progress, not one frozen state
    finally:
        stop.set()
        t.join(timeout=10)
        srv.stop()
    assert not t.is_alive()
    assert rec.phase_attribution()["n_sweeps"] >= 3


def test_server_stop_releases_port():
    run = obs.RunTelemetry()
    srv = obs.IntrospectionServer(run, port=0)
    port = srv.port
    srv.stop()
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", port))  # must be rebindable after stop()
    finally:
        s.close()


# ------------------------------------------------- cli train live endpoints


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_cli_train_trace_out_and_live_status(tmp_path):
    """End-to-end acceptance: cli train --trace-out --status-port produces a
    Perfetto-loadable trace + phase attribution whose per-sweep identity
    critical_path + other == wall holds, while /statusz and /metrics answer
    live mid-training."""
    from photon_ml_tpu.cli.train import run as train_run
    from photon_ml_tpu.io import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing import generate_mixed_effect_data
    from photon_ml_tpu.testing.generators import generate_game_records

    data = generate_mixed_effect_data(
        n=400, d_fixed=5, re_specs={"userId": (16, 4)}, seed=4
    )
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    train_path = str(tmp_path / "train.avro")
    write_avro_file(train_path, schema, generate_game_records(data))
    trace_path = str(tmp_path / "trace.json")
    port = _free_port()
    n_sweeps = 2

    result = {}

    def _train():
        result["summary"] = train_run(
            [
                "--input-data", train_path,
                "--validation-data", train_path,
                "--task", "logistic_regression",
                "--feature-shard", "name=global,bags=features",
                "--feature-shard", "name=userShard,bags=userFeatures",
                "--coordinate",
                "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1",
                "--coordinate",
                "name=per-user,shard=userShard,re.type=userId,reg.type=L2,reg.weights=1",
                "--evaluators", "AUC",
                "--coordinate-descent-iterations", str(n_sweeps),
                "--output-dir", str(tmp_path / "out"),
                "--trace-out", trace_path,
                "--status-port", str(port),
            ]
        )

    t = threading.Thread(target=_train, name="cli-train")
    t.start()
    base = f"http://127.0.0.1:{port}"
    live_statusz = []
    live_metrics = False
    deadline = time.monotonic() + 300
    while t.is_alive() and time.monotonic() < deadline:
        try:
            doc = json.loads(_get(base + "/statusz", timeout=5)[2])
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.05)
            continue
        assert doc["status"] == "ok"
        if "coordinate" in doc:
            live_statusz.append(doc)
        if not live_metrics:
            text = _get(base + "/metrics", timeout=5)[2].decode("utf-8")
            live_metrics = "photon_" in text
        time.sleep(0.02)
    t.join(timeout=300)
    assert not t.is_alive()
    assert result["summary"]["best"]["metrics"]["AUC"] > 0.6

    # the endpoints answered mid-training with live progress
    assert live_statusz, "statusz never reported a live coordinate"
    assert live_metrics, "metrics exposition never reported photon_* families"
    assert {d["coordinate"] for d in live_statusz} <= {"global", "per-user"}

    # trace file is Perfetto-loadable chrome trace with the sweep spans
    trace = json.load(open(trace_path))
    assert trace["displayTimeUnit"] == "ms"
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert sum(1 for e in xs if e["name"] == "cd.sweep") == n_sweeps
    phases_seen = {e["args"].get("phase") for e in xs} - {None}
    assert "solve" in phases_seen and "score" in phases_seen

    # run_summary.json lands next to the trace when --metrics-out is absent
    rs = json.load(open(tmp_path / "run_summary.json"))
    tl = rs["timeline"]
    assert tl["n_sweeps"] == n_sweeps
    for sweep in tl["sweeps"]:
        assert sweep["critical_path_seconds"] + sweep["other_seconds"] == pytest.approx(
            sweep["wall_seconds"], rel=1e-6
        )
        assert set(sweep["phases"]) >= {"solve", "score"}
        assert 0.0 <= sweep["overlap_factor"] < 1.0
    assert tl["total"]["wall_seconds"] > 0


# ------------------------------------------------------------ bench --diff


def _bench_record(value, quadrants=None, metric="glmix_examples_per_sec_per_chip"):
    rec = {"metric": metric, "value": value, "unit": "examples/sec/chip"}
    if quadrants is not None:
        rec["quadrants"] = quadrants
    return rec


def _write(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


def test_diff_parity_exit_zero(tmp_path, capsys):
    import bench

    old = _write(tmp_path / "old.json", _bench_record(1000.0))
    new = _write(tmp_path / "new.json", _bench_record(1010.0))
    rc = bench.run_diff_files(old, new)
    assert rc == 0
    assert "parity" in capsys.readouterr().out


def test_diff_throughput_regression_exit_one(tmp_path, capsys):
    import bench

    old = _write(tmp_path / "old.json", _bench_record(1000.0))
    new = _write(tmp_path / "new.json", _bench_record(850.0))  # -15%
    rc = bench.run_diff_files(old, new)
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "-15.00%" in out


def test_diff_throughput_improvement_is_not_regression(tmp_path):
    import bench

    old = _write(tmp_path / "old.json", _bench_record(1000.0))
    new = _write(tmp_path / "new.json", _bench_record(1500.0))
    assert bench.run_diff_files(old, new) == 0


def test_diff_tolerance_configurable(tmp_path):
    import bench

    old = _write(tmp_path / "old.json", _bench_record(1000.0))
    new = _write(tmp_path / "new.json", _bench_record(850.0))
    assert bench.run_diff_files(old, new, tolerance=0.2) == 0
    assert bench.run_diff_files(old, new, tolerance=0.1) == 1


def test_diff_quadrant_regression_lower_is_better(tmp_path, capsys):
    import bench

    q_old = {"tpu": {"warm_marginal_sec": 1.0, "cold_sweep_sec": 5.0}}
    q_new = {"tpu": {"warm_marginal_sec": 1.3, "cold_sweep_sec": 5.0}}
    old = _write(tmp_path / "old.json", _bench_record(1000.0, q_old))
    new = _write(tmp_path / "new.json", _bench_record(1000.0, q_new))
    rc = bench.run_diff_files(old, new)
    assert rc == 1
    out = capsys.readouterr().out
    assert "quadrants.tpu.warm_marginal_sec" in out
    assert "lower_is_better" in out
    assert "3 series compared" in out


def test_diff_progress_jsonl_appends(tmp_path):
    import bench

    old = _write(tmp_path / "old.json", _bench_record(1000.0))
    new = _write(tmp_path / "new.json", _bench_record(800.0))
    progress = tmp_path / "PROGRESS.jsonl"
    progress.write_text('{"type": "driver_row"}\n')
    rc = bench.run_diff_files(old, new, progress_out=str(progress))
    assert rc == 1
    lines = [json.loads(l) for l in progress.read_text().splitlines()]
    assert lines[0] == {"type": "driver_row"}  # append-only: old rows survive
    row = lines[1]
    assert row["type"] == "bench_diff" and row["regressed"] is True
    assert row["tolerance"] == pytest.approx(0.1)
    series = row["series"]["glmix_examples_per_sec_per_chip"]
    assert series["old"] == 1000.0 and series["new"] == 800.0
    assert series["delta_pct"] == pytest.approx(-20.0)


def test_diff_main_argv_exit_codes(tmp_path):
    import bench

    old = _write(tmp_path / "old.json", _bench_record(1000.0))
    good = _write(tmp_path / "good.json", _bench_record(1001.0))
    bad = _write(tmp_path / "bad.json", _bench_record(700.0))
    with pytest.raises(SystemExit) as e:
        bench.main(["--diff", old, good])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        bench.main(["--diff", old, bad])
    assert e.value.code == 1


def test_diff_unusable_inputs_exit_two(tmp_path, capsys):
    import bench

    garbage = tmp_path / "garbage.json"
    garbage.write_text("not json at all")
    ok = _write(tmp_path / "ok.json", _bench_record(1.0))
    with pytest.raises(SystemExit) as e:
        bench.run_diff_files(str(garbage), ok)
    assert e.value.code == 2
    assert "--diff" in capsys.readouterr().err

    other = _write(tmp_path / "other.json", _bench_record(1.0, metric="other_metric"))
    with pytest.raises(SystemExit) as e:
        bench.run_diff_files(ok, other)
    assert e.value.code == 2

    not_a_record = _write(tmp_path / "x.json", {"hello": "world"})
    with pytest.raises(SystemExit) as e:
        bench.run_diff_files(not_a_record, ok)
    assert e.value.code == 2


def test_diff_reads_driver_wrapper_shape(tmp_path):
    import bench

    inner = _bench_record(
        1000.0, {"tpu": {"warm_marginal_sec": 1.0}}
    )
    wrapper = {
        "n": 4,
        "cmd": "python bench.py",
        "rc": 0,
        "tail": "some log noise\n" + json.dumps(inner) + "\n",
        "parsed": {"metric": inner["metric"], "value": inner["value"]},
    }
    old = _write(tmp_path / "wrap.json", wrapper)
    rec = bench.load_bench_record(old)
    assert rec["value"] == 1000.0
    assert rec["quadrants"]["tpu"]["warm_marginal_sec"] == 1.0
    # wrapper vs raw record compare cleanly
    new = _write(
        tmp_path / "raw.json",
        _bench_record(1000.0, {"tpu": {"warm_marginal_sec": 1.0}}),
    )
    assert bench.run_diff_files(old, new) == 0
