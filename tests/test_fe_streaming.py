"""Out-of-core (streamed) FIXED-effect training parity.

The row-sliced streamed path (game/fe_streaming.py + optimize/host_driver.py)
must reproduce the resident device path: same objective decomposed into
per-slice partials, same solver semantics on the host, just pipelined through
the chip in budget-sized double-buffered row slices. Accumulation order is
fixed (left-to-right over slices), so repeated evaluations — and whole
repeated solves — are bitwise identical run-to-run.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.game import (
    CoordinateDescent,
    FixedEffectCoordinate,
    GLMOptimizationConfig,
    RandomEffectCoordinate,
    ValidationContext,
    build_fixed_effect_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.fe_streaming import (
    StreamedFEObjective,
    estimate_fe_batch_bytes,
    rows_per_slice,
    score_streamed_fe,
)
from photon_ml_tpu.evaluation import build_suite
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig, OptimizerType
from photon_ml_tpu.robust import faults
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.clear()


def _cfg(l2=0.8, optimizer="LBFGS", reg="L2", **opt_kw):
    return GLMOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer_type=OptimizerType(optimizer),
            tolerance=1e-9,
            max_iterations=80,
            **opt_kw,
        ),
        regularization=RegularizationContext(reg),
        reg_weight=l2,
    )


@pytest.fixture(scope="module")
def raw():
    return mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=700, d_fixed=6, re_specs={"userId": (20, 4)}, seed=11
        )
    )


def _pair(raw, budget_bytes=16 << 10, layout="auto", dtype=jnp.float64):
    mem = build_fixed_effect_dataset(raw, "global", "global", dtype=dtype, layout=layout)
    streamed = build_fixed_effect_dataset(
        raw, "global", "global", dtype=dtype, layout=layout,
        hbm_budget_bytes=budget_bytes,
    )
    assert streamed.streamed, "budget should force the streamed build"
    assert streamed.batch is None and streamed.host_batch is not None
    return mem, streamed


# ---------------------------------------------------------------- sizing


def test_estimate_and_rows_per_slice():
    assert estimate_fe_batch_bytes(100, 10, "dense") == 100 * 10 * 4 + 3 * 100 * 4
    # ELL: idx (i32) + val planes per row slot
    assert (
        estimate_fe_batch_bytes(100, 10, "ell", ell_width=3)
        == 100 * 3 * (4 + 4) + 3 * 100 * 4
    )
    with pytest.raises(ValueError, match="dense|ell"):
        estimate_fe_batch_bytes(1, 1, "coo")
    # budget halves for double buffering, rounds down to the row multiple
    assert rows_per_slice(8000, 100) == 40
    # floor: at least one row multiple even under an absurd budget
    assert rows_per_slice(0, 100) == 8


def test_budget_decision(raw):
    big = build_fixed_effect_dataset(
        raw, "global", "global", hbm_budget_bytes=1 << 30
    )
    assert not big.streamed and big.batch is not None
    _, streamed = _pair(raw)
    assert streamed.hbm_budget_bytes == 16 << 10
    with pytest.raises(ValueError, match="row-sliceable layout"):
        build_fixed_effect_dataset(
            raw, "global", "global", layout="coo", hbm_budget_bytes=0
        )


def test_streamed_ell_build(raw):
    _, streamed = _pair(raw, layout="ell")
    hb = streamed.host_batch
    assert hb.layout == "ell"
    assert hb.ell_idx is not None and hb.ell_val.shape == hb.ell_idx.shape


# ---------------------------------------------------------------- parity


@pytest.mark.parametrize("layout", ["dense", "ell"])
@pytest.mark.parametrize("optimizer", ["LBFGS", "TRON"])
def test_streamed_train_matches_resident(raw, layout, optimizer):
    mem, streamed = _pair(raw, layout=layout)
    cfg = _cfg(optimizer=optimizer)
    cm = FixedEffectCoordinate(dataset=mem, task="logistic_regression", config=cfg)
    cs = FixedEffectCoordinate(dataset=streamed, task="logistic_regression", config=cfg)
    m_mem, r_mem = cm.train(None)
    m_str, r_str = cs.train(None)
    np.testing.assert_allclose(
        np.asarray(m_str.model.coefficients.means),
        np.asarray(m_mem.model.coefficients.means),
        atol=1e-6,
    )
    assert abs(float(r_str.loss) - float(r_mem.loss)) < 1e-8 * max(
        1.0, abs(float(r_mem.loss))
    )


def test_streamed_owlqn_l1_parity(raw):
    mem, streamed = _pair(raw)
    cfg = _cfg(l2=0.5, reg="ELASTIC_NET")
    cm = FixedEffectCoordinate(dataset=mem, task="logistic_regression", config=cfg)
    cs = FixedEffectCoordinate(dataset=streamed, task="logistic_regression", config=cfg)
    m_mem, _ = cm.train(None)
    m_str, _ = cs.train(None)
    np.testing.assert_allclose(
        np.asarray(m_str.model.coefficients.means),
        np.asarray(m_mem.model.coefficients.means),
        atol=1e-6,
    )


def test_streamed_residual_and_warm_start_parity(raw):
    mem, streamed = _pair(raw)
    cfg = _cfg()
    cm = FixedEffectCoordinate(dataset=mem, task="logistic_regression", config=cfg)
    cs = FixedEffectCoordinate(dataset=streamed, task="logistic_regression", config=cfg)
    n = raw.n_rows
    residual = jnp.asarray(np.linspace(-0.5, 0.5, n), jnp.float64)
    m0, _ = cm.train(None)
    m_mem, r_mem = cm.train(residual, initial_model=m0)
    m_str, r_str = cs.train(residual, initial_model=m0)
    np.testing.assert_allclose(
        np.asarray(m_str.model.coefficients.means),
        np.asarray(m_mem.model.coefficients.means),
        atol=1e-6,
    )


def test_streamed_normalization_parity(raw):
    from photon_ml_tpu.ops.normalization import build_normalization
    from photon_ml_tpu.utils.stats import compute_feature_statistics

    stats = compute_feature_statistics(raw, "global")
    norm = build_normalization(
        "SCALE_WITH_STANDARD_DEVIATION", stats["mean"], stats["variance"],
        stats["max_magnitude"], intercept_index=None, dtype=jnp.float64,
    )
    mem, streamed = _pair(raw)
    cfg = _cfg()
    cm = FixedEffectCoordinate(
        dataset=mem, task="logistic_regression", config=cfg, normalization=norm
    )
    cs = FixedEffectCoordinate(
        dataset=streamed, task="logistic_regression", config=cfg, normalization=norm
    )
    m_mem, _ = cm.train(None)
    m_str, _ = cs.train(None)
    np.testing.assert_allclose(
        np.asarray(m_str.model.coefficients.means),
        np.asarray(m_mem.model.coefficients.means),
        atol=1e-6,
    )


def test_streamed_variance_refused(raw):
    _, streamed = _pair(raw)
    cs = FixedEffectCoordinate(
        dataset=streamed,
        task="logistic_regression",
        config=dataclasses.replace(_cfg(), variance_type="SIMPLE"),
    )
    with pytest.raises(ValueError, match="variance"):
        cs.train(None)


def test_streamed_down_sampling_refused(raw):
    _, streamed = _pair(raw)
    cs = FixedEffectCoordinate(
        dataset=streamed,
        task="logistic_regression",
        config=dataclasses.replace(_cfg(), down_sampling_rate=0.5),
    )
    with pytest.raises(ValueError, match="down_sampling_rate"):
        cs.train(None)


def test_streamed_score_matches_resident(raw):
    mem, streamed = _pair(raw)
    cfg = _cfg()
    cm = FixedEffectCoordinate(dataset=mem, task="logistic_regression", config=cfg)
    cs = FixedEffectCoordinate(dataset=streamed, task="logistic_regression", config=cfg)
    model, _ = cm.train(None)
    s_mem = np.asarray(cm.score(model))
    s_str = np.asarray(cs.score(model))
    np.testing.assert_allclose(s_str, s_mem, atol=1e-10)


# ---------------------------------------------------------------- stability


def test_streamed_objective_bitwise_stable(raw):
    """Fixed left-to-right slice accumulation: repeated evaluations and a
    repeated whole solve are BITWISE identical, not merely close."""
    from photon_ml_tpu.ops.losses import get_loss

    _, streamed = _pair(raw)
    hb = streamed.host_batch
    obj = StreamedFEObjective(
        get_loss("logistic_regression"), hb, 16 << 10, l2_weight=0.3
    )
    assert obj.n_slices > 1
    w = np.linspace(-0.2, 0.2, hb.dim).astype(np.float64)
    v1, g1 = obj.value_and_grad(w)
    v2, g2 = obj.value_and_grad(w)
    assert float(v1) == float(v2)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    h1 = obj.hessian_vector(w, w)
    h2 = obj.hessian_vector(w, w)
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))

    cfg = _cfg()
    cs = FixedEffectCoordinate(
        dataset=streamed, task="logistic_regression", config=cfg
    )
    ma, _ = cs.train(None)
    mb, _ = cs.train(None)
    np.testing.assert_array_equal(
        np.asarray(ma.model.coefficients.means),
        np.asarray(mb.model.coefficients.means),
    )


# ---------------------------------------------------------------- telemetry


def test_streamed_metrics_recorded(raw):
    _, streamed = _pair(raw)
    run = obs.RunTelemetry()
    with obs.use_run(run):
        cs = FixedEffectCoordinate(
            dataset=streamed, task="logistic_regression", config=_cfg()
        )
        _, result = cs.train(None)
    snap = {
        (m["name"], m["labels"].get("site"), m["labels"].get("kind")): m
        for m in run.registry.snapshot()
    }
    staged = snap[("photon_stream_staged_bytes_total", "fe.train", None)]["value"]
    assert staged > 0
    n_slices = snap[("photon_stream_slices_total", "fe.train", None)]["value"]
    assert n_slices >= 2
    vg = snap[("photon_stream_passes_total", "fe.train", "vg")]["value"]
    assert vg >= result.iterations
    # every staged slice stayed within the double-buffered budget
    actual = snap[("photon_stream_actual_slice_bytes", "fe.train", None)]["value"]
    assert 0 < 2 * actual <= 16 << 10
    # the overlap claim is measured: stage wall and solve wall are both
    # recorded, and staging (H2D) must not dominate the solve
    stage_s = snap[("photon_stream_stage_seconds", "fe.train", None)]["value"]
    solve_s = snap[("photon_stream_solve_seconds", "fe.train", None)]["value"]
    assert 0 <= stage_s <= solve_s


# ---------------------------------------------------- satellite: RE scoring


def test_re_streamed_score_work_flat_in_slice_count(raw):
    """Satellite: streamed RE scoring is O(n) per sweep — the regrouped
    device work (padded gathered rows) stays <= 2n no matter how many slices
    the budget forces, where the old masked sweep did n * n_slices work."""
    kw = dict(active_cap=64, dtype=jnp.float64)
    mem = build_random_effect_dataset(raw, "re", "userShard", "userId", **kw)
    cm = RandomEffectCoordinate(dataset=mem, task="logistic_regression", config=_cfg())
    model, _ = cm.train(None)
    ref = np.asarray(cm.score(model))

    n = raw.n_rows
    work = []
    for budget in (16 << 10, 1 << 10):  # few slices vs many slices
        ds = build_random_effect_dataset(
            raw, "re", "userShard", "userId", hbm_budget_bytes=budget, **kw
        )
        assert ds.streamed
        cs = RandomEffectCoordinate(
            dataset=ds, task="logistic_regression", config=_cfg()
        )
        scores = np.asarray(cs.score(model))
        np.testing.assert_allclose(scores, ref, atol=1e-8)
        cache = getattr(ds, "_stream_xsub_cache", None)
        assert cache is not None and cache.device_rows > 0
        work.append(cache.device_rows)
        assert cache.device_rows <= 2 * n
    # more slices must NOT mean more per-sweep device work
    assert work[1] <= 2 * n and work[0] <= 2 * n


# ---------------------------------------------------------------- defense


def test_streamed_fe_divergence_rejection(raw):
    """The NaN fault + divergence guard keep working on the streamed path:
    corrupting the streamed FE solver's input freezes the solve at w0 with
    NUMERICAL_DIVERGENCE and the coordinate update is rejected."""
    _, streamed = _pair(raw)
    re_ds = build_random_effect_dataset(
        raw, "per-user", "userShard", "userId", dtype=jnp.float64
    )
    run = obs.RunTelemetry()
    with obs.use_run(run):
        coords = {
            "global": FixedEffectCoordinate(
                dataset=streamed, task="logistic_regression", config=_cfg()
            ),
            "per-user": RandomEffectCoordinate(
                dataset=re_ds, task="logistic_regression", config=_cfg()
            ),
        }
        validation = ValidationContext(
            suite=build_suite(["LOGISTIC_LOSS"], raw.labels),
            score_fns={name: coords[name].score for name in coords},
            offsets=raw.offsets,
        )
        faults.configure("solver.value_and_grad:nan:1")
        result = CoordinateDescent(coords, n_iterations=2, validation=validation).run()
        rejected = (
            run.registry.counter("photon_coordinate_rejections_total", "")
            .labels(coordinate="global")
            .value
        )
    assert rejected == 1
    for name in coords:
        assert np.isfinite(np.asarray(coords[name].score(result.model[name]))).all()


def test_streamed_fe_checkpoint_resume(raw, tmp_path):
    """Boundary checkpoints keep working with a streamed FE coordinate: a
    fresh descent resumed from the first boundary reproduces the
    uninterrupted run."""
    from photon_ml_tpu.robust import CheckpointManager

    def make():
        _, streamed = _pair(raw)
        re_ds = build_random_effect_dataset(
            raw, "per-user", "userShard", "userId", dtype=jnp.float64
        )
        coords = {
            "global": FixedEffectCoordinate(
                dataset=streamed, task="logistic_regression", config=_cfg()
            ),
            "per-user": RandomEffectCoordinate(
                dataset=re_ds, task="logistic_regression", config=_cfg()
            ),
        }
        return coords

    coords = make()
    ref = CoordinateDescent(coords, n_iterations=2, validation=None).run()

    ckpt = str(tmp_path / "ck")
    mgr = CheckpointManager(ckpt, fsync=False)
    coords2 = make()
    CoordinateDescent(
        coords2, n_iterations=2, validation=None, boundary_fn=mgr.on_boundary
    ).run()
    snap = CheckpointManager(ckpt, fsync=False).latest_valid(
        expect_coordinate_order=list(coords2), expect_n_iterations=2
    )
    assert snap is not None
    coords3 = make()
    resumed = CoordinateDescent(
        coords3, n_iterations=2, validation=None, resume_state=snap
    ).run()
    for name in coords:
        np.testing.assert_allclose(
            np.asarray(coords[name].score(ref.model[name])),
            np.asarray(coords[name].score(resumed.model[name])),
            atol=1e-8,
        )


# ---------------------------------------------------------------- e2e CLI


def test_cli_trains_streamed_fe_with_parity(tmp_path):
    """Acceptance e2e: an FE coordinate whose feature matrix exceeds an
    artificially shrunk hbm.budget.mb trains STREAMED through cli.train —
    multi-part input exercises the chunked pipelined ingest — and reproduces
    the in-memory run's quality within dtype tolerances."""
    from photon_ml_tpu.cli.train import run as train_run
    from photon_ml_tpu.io import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing.generators import generate_game_records

    data = generate_mixed_effect_data(
        n=600, d_fixed=6, re_specs={"userId": (24, 5)}, seed=4, entity_skew=1.4
    )
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    recs = generate_game_records(data)
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    for i in range(3):  # multi-part: the chunked reader pipelines over parts
        write_avro_file(
            str(train_dir / f"part-{i:05d}.avro"), schema, recs[i * 200 : (i + 1) * 200]
        )

    args = [
        "--input-data", str(train_dir),
        "--validation-data", str(train_dir),
        "--task", "logistic_regression",
        "--feature-shard", "name=global,bags=features",
        "--feature-shard", "name=userShard,bags=userFeatures",
        "--coordinate",
        "name=per-user,shard=userShard,re.type=userId,reg.type=L2,reg.weights=1",
        "--evaluators", "AUC",
    ]
    fe_coord = "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1"

    out_mem = str(tmp_path / "out-mem")
    s_mem = train_run(args + ["--coordinate", fe_coord, "--output-dir", out_mem])
    out_str = str(tmp_path / "out-streamed")
    # zero budget: far below the feature matrix footprint => streamed FE
    s_str = train_run(
        args
        + ["--coordinate", fe_coord + ",hbm.budget.mb=0", "--output-dir", out_str]
    )
    assert abs(s_str["best"]["metrics"]["AUC"] - s_mem["best"]["metrics"]["AUC"]) < 1e-3


def test_score_streamed_fe_direct(raw):
    """score_streamed_fe agrees with a resident matvec bit-for-bit at f64."""
    mem, streamed = _pair(raw)
    hb = streamed.host_batch
    w = jnp.asarray(np.linspace(-0.3, 0.3, hb.dim), jnp.float64)
    ref = np.asarray(mem.batch.features.matvec(w))
    out = np.asarray(score_streamed_fe(hb, w, 16 << 10, jnp.float64))
    np.testing.assert_allclose(out, ref, atol=1e-12)


def test_disk_to_slice_streamed_fit_matches_memory_path(tmp_path):
    """Acceptance: a streamed-FE fit fed by the disk→slice path (part files
    decoded across the ingest worker pool straight into preallocated
    HostRowBatch planes — no RawDataset ever materializes) is BIT-identical
    to one fed by the in-memory builder, host planes and trained
    coefficients alike, at any worker count."""
    from photon_ml_tpu.game.data import build_fixed_effect_dataset_from_disk
    from photon_ml_tpu.io import (
        FeatureShardConfig,
        read_avro_dataset_chunked,
        write_avro_file,
    )
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing.generators import generate_game_records

    data = generate_mixed_effect_data(n=240, d_fixed=6, re_specs={}, seed=7)
    recs = generate_game_records(data)
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    for i in range(4):
        write_avro_file(
            str(train_dir / f"part-{i:05d}.avro"),
            TRAINING_EXAMPLE_AVRO,
            recs[i * 60 : (i + 1) * 60],
        )
    shards = {"global": FeatureShardConfig(feature_bags=("features",))}
    raw, maps = read_avro_dataset_chunked(str(train_dir), shards, engine="python")
    budget = 4 << 10  # far below the matrix footprint => streamed row slices
    mem = build_fixed_effect_dataset(
        raw, "global", "global", dtype=jnp.float64, hbm_budget_bytes=budget
    )
    assert mem.streamed
    disk, _ = build_fixed_effect_dataset_from_disk(
        str(train_dir), shards, "global", "global", budget,
        index_maps=maps, dtype=jnp.float64, workers=3,
    )
    assert disk.streamed
    assert mem.host_batch.dense.tobytes() == disk.host_batch.dense.tobytes()
    np.testing.assert_array_equal(mem.host_batch.labels, disk.host_batch.labels)

    cfg = _cfg()
    m_mem, _ = FixedEffectCoordinate(
        dataset=mem, task="logistic_regression", config=cfg
    ).train(None)
    m_disk, _ = FixedEffectCoordinate(
        dataset=disk, task="logistic_regression", config=cfg
    ).train(None)
    assert (
        np.asarray(m_mem.model.coefficients.means).tobytes()
        == np.asarray(m_disk.model.coefficients.means).tobytes()
    )


def test_disk_to_slice_refuses_non_row_sliceable_layout(tmp_path):
    from photon_ml_tpu.game.data import build_fixed_effect_dataset_from_disk
    from photon_ml_tpu.io import FeatureShardConfig, write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing.generators import generate_game_records

    data = generate_mixed_effect_data(n=40, d_fixed=4, re_specs={}, seed=9)
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    write_avro_file(
        str(train_dir / "part-00000.avro"),
        TRAINING_EXAMPLE_AVRO,
        generate_game_records(data),
    )
    shards = {"global": FeatureShardConfig(feature_bags=("features",))}
    with pytest.raises(ValueError, match="requires a row-sliceable layout"):
        build_fixed_effect_dataset_from_disk(
            str(train_dir), shards, "global", "global", 1 << 20, layout="coo"
        )
