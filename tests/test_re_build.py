"""Vectorized random-effect dataset build: exact equality against a
straightforward per-entity loop reference, plus a scale smoke test
(VERDICT.md round-1 item 3: no per-entity Python loops, millions of entities
in seconds)."""

import time

import numpy as np
import pytest

from photon_ml_tpu.game import build_random_effect_dataset
from photon_ml_tpu.game.data import _hash64, _rows_to_ell
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset


def _loop_reference_blocks(raw, feature_shard, re_type, active_cap, lower_bound, seed):
    """The pre-vectorization per-entity loop implementation, kept as the
    semantic reference."""
    n = raw.n_rows
    ids = raw.id_tags[re_type]
    rows, cols, vals = raw.shard_coo[feature_shard]
    uniq, inv = np.unique(ids.astype(str), return_inverse=True)
    counts = np.bincount(inv, minlength=len(uniq))
    kept_mask = counts >= lower_bound
    kept_entities = np.nonzero(kept_mask)[0]
    kept_entities = kept_entities[np.argsort(-counts[kept_entities], kind="stable")]
    E = len(kept_entities)
    old_to_block = np.full(len(uniq), -1, dtype=np.int64)
    old_to_block[kept_entities] = np.arange(E)
    cap = active_cap if active_cap is not None else int(counts.max() if len(counts) else 1)
    K = int(min(int(counts[kept_entities].max()) if E else 1, cap)) or 1

    row_ids = np.arange(n, dtype=np.int64)
    priority = _hash64(row_ids, seed)
    entity_of_row = old_to_block[inv]
    order = np.lexsort((priority, entity_of_row))
    sorted_rows = row_ids[order]
    sorted_entity = entity_of_row[order]
    starts = np.searchsorted(sorted_entity, np.arange(E))
    rank = np.arange(n) - starts[np.clip(sorted_entity, 0, max(E - 1, 0))]
    is_active = (sorted_entity >= 0) & (rank < K)

    active_rows = np.full((E, K), -1, dtype=np.int64)
    weight_scale = np.ones(E)
    for e in range(E):
        cnt = counts[kept_entities[e]]
        if cnt > cap:
            weight_scale[e] = cnt / cap
    s = np.nonzero(is_active)[0]
    active_rows[sorted_entity[s], rank[s]] = sorted_rows[s]

    ell_idx, ell_val = _rows_to_ell(rows, cols, vals, n)
    S = 1
    per_entity_cols = []
    for e in range(E):
        r = active_rows[e]
        r = r[r >= 0]
        c = np.unique(ell_idx[r][ell_val[r] != 0])
        per_entity_cols.append(c)
        S = max(S, len(c))
    proj_cols = np.full((E, S), -1, dtype=np.int32)
    for e in range(E):
        c = per_entity_cols[e]
        proj_cols[e, : len(c)] = c

    feats = np.zeros((E, K, S))
    labels = np.zeros((E, K))
    offsets = np.zeros((E, K))
    weights = np.zeros((E, K))
    for e in range(E):
        ks = np.nonzero(active_rows[e] >= 0)[0]
        r = active_rows[e, ks]
        labels[e, ks] = raw.labels[r]
        offsets[e, ks] = raw.offsets[r]
        weights[e, ks] = raw.weights[r] * weight_scale[e]
        cols_e = per_entity_cols[e]
        if len(cols_e) == 0:
            continue
        fi = ell_idx[r]
        fv = ell_val[r]
        pos = np.clip(np.searchsorted(cols_e, fi), 0, len(cols_e) - 1)
        hit = (cols_e[pos] == fi) & (fv != 0.0)
        kk, ff = np.nonzero(hit)
        feats[e, ks[kk], pos[kk, ff]] = fv[kk, ff]
    return feats, labels, offsets, weights, proj_cols, active_rows


@pytest.mark.parametrize("active_cap,lower_bound", [(None, 1), (8, 1), (8, 3)])
def test_vectorized_build_equals_loop_reference(active_cap, lower_bound):
    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=600, d_fixed=4, re_specs={"userId": (40, 6)}, seed=7, entity_skew=1.4
        )
    )
    ds = build_random_effect_dataset(
        raw, "re", "userShard", "userId",
        active_cap=active_cap, active_lower_bound=lower_bound, seed=3,
    )
    feats, labels, offsets, weights, proj_cols, active_rows = _loop_reference_blocks(
        raw, "userShard", "userId", active_cap, lower_bound, seed=3
    )
    b = ds.blocks
    np.testing.assert_array_equal(np.asarray(b.proj_cols), proj_cols)
    np.testing.assert_array_equal(np.asarray(b.active_rows), active_rows.astype(np.int32))
    np.testing.assert_allclose(np.asarray(b.features), feats, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b.labels), labels, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b.offsets), offsets, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b.weights), weights, rtol=1e-6)


def test_build_scales_to_many_entities():
    """1M entities / 5M rows must build in seconds (host pass is O(nnz log);
    the round-1 loop implementation was O(entities) Python iterations)."""
    n, E = 5_000_000, 1_000_000
    rng = np.random.default_rng(0)
    ids = rng.integers(0, E, size=n)
    d_re = 4
    rows = np.repeat(np.arange(n), d_re)
    cols = np.tile(np.arange(d_re), n)
    vals = rng.normal(size=n * d_re)
    from photon_ml_tpu.io.data import RawDataset

    raw = RawDataset(
        n_rows=n,
        labels=(rng.uniform(size=n) < 0.5).astype(np.float64),
        offsets=np.zeros(n),
        weights=np.ones(n),
        shard_coo={"s": (rows, cols, vals)},
        shard_dims={"s": d_re},
        id_tags={"userId": ids.astype(str)},
    )
    t0 = time.perf_counter()
    ds = build_random_effect_dataset(raw, "re", "s", "userId", active_cap=16)
    dt = time.perf_counter() - t0
    assert ds.blocks.features.shape[0] >= E * 0.99
    assert dt < 120.0, f"RE build took {dt:.1f}s"


def _bucketed_vs_flat(monkeypatch, solver: str):
    import dataclasses as dc

    from photon_ml_tpu.game import (
        GLMOptimizationConfig,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.coordinate import _size_buckets
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optimize import OptimizerConfig

    monkeypatch.setenv("PHOTON_RE_SOLVER", solver)
    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=1500, d_fixed=4, re_specs={"userId": (60, 8)}, seed=11, entity_skew=1.6
        )
    )
    ds = build_random_effect_dataset(raw, "re", "userShard", "userId", active_cap=64)
    assert _size_buckets(ds) is not None and len(_size_buckets(ds)) > 1
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-10, max_iterations=60),
        regularization=RegularizationContext("L2"),
        reg_weight=0.5,
    )
    coord = RandomEffectCoordinate(dataset=ds, task="logistic_regression", config=cfg)
    m_bucketed, r_bucketed = coord.train(None)

    ds_flat = dc.replace(ds, entity_counts=None, entity_subspace_dims=None)
    coord_flat = RandomEffectCoordinate(
        dataset=ds_flat, task="logistic_regression", config=cfg
    )
    m_flat, r_flat = coord_flat.train(None)
    return m_bucketed, r_bucketed, m_flat, r_flat


def test_size_bucketed_solve_equals_single_block(monkeypatch):
    """Bucketed per-size solves must reproduce the single-block solve exactly
    on the vmapped solver (padding rows/cols are mathematically inert and
    each vmap lane's op shapes are bucket-independent)."""
    m_bucketed, r_bucketed, m_flat, r_flat = _bucketed_vs_flat(monkeypatch, "vmapped")
    np.testing.assert_allclose(
        np.asarray(m_bucketed.coef_values), np.asarray(m_flat.coef_values), atol=1e-12
    )
    np.testing.assert_array_equal(
        np.asarray(r_bucketed.iterations), np.asarray(r_flat.iterations)
    )


def test_size_bucketed_solve_matches_single_block_packed(monkeypatch):
    """The entity-minor packed solver reduces over the K axis with
    bucket-dependent tree shapes, so bucketed vs flat agree to optimization
    tolerance (same optimum) rather than bit-exactly."""
    m_bucketed, r_bucketed, m_flat, r_flat = _bucketed_vs_flat(monkeypatch, "packed")
    np.testing.assert_allclose(
        np.asarray(m_bucketed.coef_values),
        np.asarray(m_flat.coef_values),
        atol=2e-3,
    )
    np.testing.assert_allclose(
        np.asarray(r_bucketed.loss), np.asarray(r_flat.loss), rtol=1e-5, atol=1e-6
    )


class TestGlobalBuildParity:
    """game/data_mp.build_random_effect_dataset_global run single-process must
    reproduce the host numpy build bit-for-bit: the multi-process path's
    planning (entity order, reservoir, subspace projection) is the same
    algorithm re-expressed as a device sort/gather pipeline, and this parity
    is what certifies it before the 2-process test exercises the exchange."""

    def _raw(self, n=700, seed=5, n_entities=60, d_re=9):
        return mixed_data_to_raw_dataset(
            generate_mixed_effect_data(
                n=n, d_fixed=4, re_specs={"userId": (n_entities, d_re)},
                seed=seed, entity_skew=1.4,
            )
        )

    @pytest.mark.parametrize(
        "cap,lower", [(None, 1), (6, 1), (None, 3), (4, 2)]
    )
    def test_exact_parity(self, cap, lower):
        from photon_ml_tpu.game.data_mp import build_random_effect_dataset_global
        from photon_ml_tpu.parallel.mesh import make_mesh

        raw = self._raw()
        kw = dict(
            active_cap=cap, active_lower_bound=lower, pad_entities_to_multiple=8
        )
        a = build_random_effect_dataset(raw, "re", "userShard", "userId", **kw)
        b = build_random_effect_dataset_global(
            raw, "re", "userShard", "userId", mesh=make_mesh(n_data=8), **kw
        )
        n = raw.n_rows
        assert list(a.entity_ids) == list(b.entity_ids)
        np.testing.assert_array_equal(
            np.asarray(a.blocks.active_rows), np.asarray(b.blocks.active_rows)
        )
        np.testing.assert_array_equal(
            np.asarray(a.blocks.proj_cols), np.asarray(b.blocks.proj_cols)
        )
        np.testing.assert_array_equal(b.host_proj_cols, np.asarray(b.blocks.proj_cols))
        for f in ("features", "labels", "offsets", "weights"):
            np.testing.assert_allclose(
                np.asarray(getattr(a.blocks, f)),
                np.asarray(getattr(b.blocks, f)),
                rtol=1e-6,
                err_msg=f,
            )
        # b's row space is padded to the mesh row multiple; pad rows map to no
        # entity
        np.testing.assert_array_equal(
            np.asarray(a.row_entity), np.asarray(b.row_entity)[:n]
        )
        assert np.all(np.asarray(b.row_entity)[n:] == -1)
        F = a.ell_idx.shape[1]
        np.testing.assert_array_equal(
            np.asarray(a.ell_idx), np.asarray(b.ell_idx)[:n, :F]
        )
        np.testing.assert_allclose(
            np.asarray(a.ell_val), np.asarray(b.ell_val)[:n, :F], rtol=1e-6
        )
        np.testing.assert_array_equal(a.entity_counts, b.entity_counts)
        np.testing.assert_array_equal(
            a.entity_subspace_dims, b.entity_subspace_dims
        )
        # passive accounting matches the host build (VERDICT r4 weak item 7:
        # the mp build derives it instead of leaving the field empty)
        np.testing.assert_array_equal(
            np.sort(np.asarray(a.passive_rows, dtype=np.int64)),
            np.sort(np.asarray(b.passive_rows, dtype=np.int64)),
        )

    def test_pearson_selection_agrees(self):
        """Pearson selection: counts must match exactly; the kept COLUMNS may
        differ only where scores tie exactly (host/device summation order
        breaks exact ties differently — see data_mp docstring)."""
        from photon_ml_tpu.game.data_mp import build_random_effect_dataset_global
        from photon_ml_tpu.parallel.mesh import make_mesh

        raw = self._raw(n=900, seed=9)
        kw = dict(
            active_cap=8, pad_entities_to_multiple=8, features_to_samples_ratio=0.5
        )
        a = build_random_effect_dataset(raw, "re", "userShard", "userId", **kw)
        b = build_random_effect_dataset_global(
            raw, "re", "userShard", "userId", mesh=make_mesh(n_data=8), **kw
        )
        np.testing.assert_array_equal(
            a.entity_subspace_dims, b.entity_subspace_dims
        )
        pa, pb = np.asarray(a.blocks.proj_cols), np.asarray(b.blocks.proj_cols)
        agree = (pa == pb).mean()
        assert agree > 0.9, f"kept-column agreement {agree:.3f}"

    def test_training_on_global_build_matches(self, monkeypatch):
        """A full RE coordinate train on the device-built dataset equals the
        numpy-built one (same blocks => same solves). Pinned to the vmapped
        solver: it is bit-exact across shard-aligned bucket shapes, so any
        difference here indicts the BUILD, not solver reduction order (the
        packed solver's bucket-shape sensitivity is covered separately in
        test_size_bucketed_solve_matches_single_block_packed)."""
        import dataclasses as dc

        monkeypatch.setenv("PHOTON_RE_SOLVER", "vmapped")

        from photon_ml_tpu.game import (
            GLMOptimizationConfig,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.game.data_mp import build_random_effect_dataset_global
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.optimize import OptimizerConfig
        from photon_ml_tpu.parallel.mesh import make_mesh

        raw = self._raw(n=800, seed=13)
        mesh = make_mesh(n_data=8)
        kw = dict(active_cap=32, pad_entities_to_multiple=8)
        a = build_random_effect_dataset(raw, "re", "userShard", "userId", **kw)
        b = build_random_effect_dataset_global(
            raw, "re", "userShard", "userId", mesh=mesh, **kw
        )
        cfg = GLMOptimizationConfig(
            optimizer=OptimizerConfig(tolerance=1e-10, max_iterations=50),
            regularization=RegularizationContext("L2"),
            reg_weight=0.7,
        )
        ma, ra = RandomEffectCoordinate(
            dataset=a, task="logistic_regression", config=cfg
        ).train(None)
        mb, rb = RandomEffectCoordinate(
            dataset=b, task="logistic_regression", config=cfg
        ).train(None)
        np.testing.assert_allclose(
            np.asarray(ma.coef_values), np.asarray(mb.coef_values), atol=1e-10
        )
        # scoring through the padded global row space matches on true rows
        sa = np.asarray(RandomEffectCoordinate(
            dataset=a, task="logistic_regression", config=cfg
        ).score(ma))
        sb = np.asarray(RandomEffectCoordinate(
            dataset=b, task="logistic_regression", config=cfg
        ).score(mb))
        np.testing.assert_allclose(sa, sb[: raw.n_rows], atol=1e-10)
        assert np.all(sb[raw.n_rows:] == 0.0)
