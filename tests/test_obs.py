"""Observability layer tests: metrics registry semantics, span nesting,
sink output schemas, EventEmitter error isolation, the jax compile hook,
the CD hot-loop zero-fetch invariant, and the cli.train --metrics-out
integration surface (metrics.jsonl / metrics.prom / run_summary.json)."""

import json
import logging
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.estimators import CoordinateConfig, GameEstimator
from photon_ml_tpu.game.problem import GLMOptimizationConfig
from photon_ml_tpu.obs.metrics import MetricsRegistry, render_prometheus
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.optimize.trackers import StatCounter
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset
from photon_ml_tpu.utils.events import Event, EventListener
from photon_ml_tpu.utils.timed import timed


# ---------------------------------------------------------------- registry


def test_counter_and_gauge():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", "requests").labels(path="/train")
    c.inc()
    c.inc(4)
    g = reg.gauge("queue_depth", "depth")
    g.set(7.5)
    g.inc(-2.5)
    snap = {(m["name"], tuple(sorted(m["labels"].items()))): m for m in reg.snapshot()}
    assert snap[("requests_total", (("path", "/train"),))]["value"] == 5
    assert snap[("queue_depth", ())]["value"] == 5.0


def test_counter_same_labels_same_child():
    reg = MetricsRegistry()
    a = reg.counter("c", "").labels(x="1", y="2")
    b = reg.counter("c", "").labels(y="2", x="1")
    assert a is b


def test_histogram_bucket_cumulation():
    reg = MetricsRegistry()
    h = reg.histogram("latency", "l", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    (m,) = reg.snapshot()
    assert m["count"] == 5
    assert m["sum"] == pytest.approx(56.05)
    # buckets are cumulative: le=0.1 -> 1, le=1.0 -> 3, le=10.0 -> 4
    assert m["buckets"] == [[0.1, 1], [1.0, 3], [10.0, 4]]


def test_summary_merge_stat_matches_statcounter_of_concat():
    a = np.array([3.0, 7.0, 7.0, 11.0])
    b = np.array([1.0, 2.0, 20.0])
    reg = MetricsRegistry()
    s = reg.summary("iters", "")
    for st in (StatCounter.of(a), StatCounter.of(b)):
        s.merge_stat(st.count, st.mean, st.stdev, st.max, st.min)
    got = s.stat()
    want = StatCounter.of(np.concatenate([a, b]))
    assert got["count"] == want.count
    assert got["mean"] == pytest.approx(want.mean)
    assert got["stdev"] == pytest.approx(want.stdev)
    assert got["max"] == want.max and got["min"] == want.min


def test_summary_observe_many():
    reg = MetricsRegistry()
    s = reg.summary("s", "")
    s.observe_many([1.0, 2.0, 3.0])
    s.observe(10.0)
    assert s.stat()["count"] == 4
    assert s.stat()["max"] == 10.0


def test_reregister_different_kind_raises():
    reg = MetricsRegistry()
    reg.counter("x", "")
    with pytest.raises(TypeError):
        reg.gauge("x", "")


def test_render_prometheus_escaping_and_shapes():
    reg = MetricsRegistry()
    reg.counter("hits_total", "all the hits").labels(path='a"b\\c\nd').inc(3)
    reg.histogram("lat", "lat", buckets=(1.0,)).observe(0.5)
    reg.summary("iters", "it").observe_many([2.0, 4.0])
    text = render_prometheus(reg.snapshot())
    assert '# TYPE hits_total counter' in text
    assert 'path="a\\"b\\\\c\\nd"' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_sum 0.5" in text and "lat_count 1" in text
    assert "iters_sum 6" in text and "iters_count 2" in text
    assert "iters_mean 3" in text


def test_render_prometheus_help_lines_and_hostile_labels():
    """Exposition grammar: # HELP precedes # TYPE per family, help text
    escapes backslash-then-newline, and hostile label values (quotes,
    backslashes, newlines, unicode) survive the escaping round trip."""
    reg = MetricsRegistry()
    reg.histogram(
        "train_lat", "help with\nnewline and \\ backslash", buckets=(0.1, 1.0)
    ).observe(0.5)
    hostile = 'per"user\\x\ny\tzé'
    reg.counter("c_total", "counts").labels(coordinate=hostile).inc()
    text = render_prometheus(reg.snapshot())
    lines = text.splitlines()
    assert "# HELP c_total counts" in lines
    assert lines.index("# HELP c_total counts") + 1 == lines.index(
        "# TYPE c_total counter"
    )
    # help escaping: \ -> \\ first, then newline -> \n (no raw newlines)
    assert "# HELP train_lat help with\\nnewline and \\\\ backslash" in lines
    assert 'c_total{coordinate="per\\"user\\\\x\\ny\tzé"} 1' in lines
    # exposition grammar: every non-comment line is `name{labels} value`
    # with no unescaped newline inside a label value
    for line in lines:
        if not line or line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert name and name[0].isalpha()
        assert line.count(" ") >= 1


def test_quantile_gauges_for_all_histograms():
    """p50/p95/p99 gauges render for every histogram family, not just
    photon_serving_*."""
    reg = MetricsRegistry()
    h = reg.histogram(
        "photon_stream_slice_stage_seconds", "stage wall", buckets=(0.1, 1.0, 10.0)
    )
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    text = render_prometheus(reg.snapshot())
    assert "# TYPE photon_stream_slice_stage_seconds_p50 gauge" in text
    assert "photon_stream_slice_stage_seconds_p95" in text
    assert "photon_stream_slice_stage_seconds_p99" in text
    # p50 falls in the (0.1, 1.0] bucket under linear interpolation
    p50 = [
        float(l.split()[-1])
        for l in text.splitlines()
        if l.startswith("photon_stream_slice_stage_seconds_p50 ")
    ][0]
    assert 0.1 < p50 <= 1.0


# ------------------------------------------------------------ spans/tracing


class _Collector(EventListener):
    def __init__(self):
        self.events = []

    def handle(self, event: Event) -> None:
        self.events.append(event)


def test_span_nesting_parent_ids():
    run = obs.RunTelemetry()
    col = _Collector()
    run.register_listener(col)
    with obs.use_run(run):
        with obs.span("outer", k=1):
            with obs.span("inner"):
                pass
            with obs.span("inner2"):
                pass
    spans = {e.span.name: e.span for e in col.events if isinstance(e, obs.SpanEvent)}
    assert set(spans) == {"outer", "inner", "inner2"}
    assert spans["outer"].parent_id is None
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["inner2"].parent_id == spans["outer"].span_id
    assert spans["inner"].span_id != spans["inner2"].span_id
    assert spans["outer"].attrs["k"] == 1
    assert spans["outer"].duration_s >= spans["inner"].duration_s >= 0


def test_span_without_listeners_emits_nothing_and_is_cheap():
    # passive default run: span() must not emit or fail
    with obs.span("quiet"):
        assert obs.current_span().name == "quiet"
    assert obs.current_span() is None


def test_timed_produces_span_and_log(caplog):
    run = obs.RunTelemetry()
    col = _Collector()
    run.register_listener(col)
    with obs.use_run(run), caplog.at_level(logging.DEBUG, logger="photon_ml_tpu"):
        with timed("work unit"):
            pass
    assert any(
        isinstance(e, obs.SpanEvent) and e.span.name == "work unit" for e in col.events
    )
    assert any("work unit took" in r.getMessage() for r in caplog.records)


def test_device_transfer_counters_tagged_on_span():
    run = obs.RunTelemetry()
    col = _Collector()
    run.register_listener(col)
    with obs.use_run(run):
        with obs.span("xfer"):
            obs.add_device_fetch_bytes("test_site", 128)
            obs.add_device_fetch_bytes("test_site", 64)
            obs.add_device_put_bytes("test_site", 256)
        snap = {
            (m["name"], m["labels"].get("site")): m for m in run.registry.snapshot()
        }
    (ev,) = [e for e in col.events if isinstance(e, obs.SpanEvent)]
    assert ev.span.attrs["fetch_bytes"] == 192
    assert ev.span.attrs["put_bytes"] == 256
    assert snap[("photon_device_fetch_bytes_total", "test_site")]["value"] == 192
    assert snap[("photon_device_put_bytes_total", "test_site")]["value"] == 256


def test_use_run_restores_previous():
    before = obs.current_run()
    with obs.use_run(obs.RunTelemetry()) as run:
        assert obs.current_run() is run
    assert obs.current_run() is before


# ------------------------------------------------------------------- sinks


def test_jsonl_sink_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    run = obs.RunTelemetry()
    run.register_listener(obs.JsonlSink(path))
    with obs.use_run(run):
        with obs.span("a", coordinate="g"):
            with obs.span("b"):
                pass
        run.registry.counter("c_total", "").inc()
        run.flush_metrics()
    run.close()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) >= 3  # two spans + one metrics flush
    spans = {l["name"]: l for l in lines if l["type"] == "span"}
    assert spans["b"]["parent_id"] == spans["a"]["span_id"]
    assert spans["a"]["attrs"]["coordinate"] == "g"
    assert all("duration_s" in s and "start_unix" in s for s in spans.values())
    # one explicit flush plus the final flush from close()
    mlines = [l for l in lines if l["type"] == "metrics"]
    assert mlines
    assert {
        "name": "c_total", "kind": "counter", "help": "", "labels": {}, "value": 1
    } in mlines[0]["metrics"]


def test_jsonl_sink_stamps_host_identity(tmp_path):
    """Every JSONL event carries process_index + host so multi-host streams
    can be merged without guessing which process wrote which line."""
    import socket

    path = str(tmp_path / "m.jsonl")
    run = obs.RunTelemetry()
    run.register_listener(obs.JsonlSink(path))
    obs.set_process_index(3)
    try:
        with obs.use_run(run):
            with obs.span("a"):
                pass
            run.registry.counter("c_total", "").inc()
            run.flush_metrics()
        run.close()  # final flush happens while the index is still set
    finally:
        obs.set_process_index(0)
    lines = [json.loads(l) for l in open(path)]
    assert lines
    for line in lines:
        assert line["process_index"] == 3
        assert line["host"] == socket.gethostname()
    (span_line,) = [l for l in lines if l["type"] == "span"]
    assert isinstance(span_line["thread_id"], int)


def test_jsonl_sink_serializes_device_arrays_as_placeholders(tmp_path):
    # events carrying device arrays must neither crash nor force a fetch of
    # array *contents* into giant JSON blobs
    path = str(tmp_path / "m.jsonl")
    run = obs.RunTelemetry()
    run.register_listener(obs.JsonlSink(path))
    with obs.use_run(run):
        with obs.span("s", arr=jnp.zeros((4,))):
            pass
    run.close()
    (line,) = [
        l for l in (json.loads(x) for x in open(path)) if l["type"] == "span"
    ]
    assert line["attrs"]["arr"].startswith("<")  # placeholder, not the data


def test_prometheus_sink_writes_exposition(tmp_path):
    path = str(tmp_path / "m.prom")
    run = obs.RunTelemetry()
    run.register_listener(obs.PrometheusSink(path))
    run.registry.counter("photon_test_total", "t").inc(2)
    run.flush_metrics()
    run.close()
    text = open(path).read()
    assert "# TYPE photon_test_total counter" in text
    assert "photon_test_total 2" in text


class _RaisingSink(EventListener):
    def __init__(self):
        self.calls = 0

    def handle(self, event: Event) -> None:
        self.calls += 1
        raise RuntimeError("sink exploded")


def test_raising_sink_never_fails_training(game_fit_data, caplog):
    train, val = game_fit_data
    sink = _RaisingSink()
    run = obs.RunTelemetry()
    run.register_listener(sink)
    est = _small_estimator()
    est.register_listener(sink)
    with obs.use_run(run), caplog.at_level(logging.ERROR, logger="photon_ml_tpu"):
        results = est.fit(train, validation=val)
    assert results[0].evaluation.metrics["AUC"] > 0.6
    assert sink.calls > 0  # it was invoked and raised, yet training finished
    assert any("sink exploded" in str(r.exc_info) for r in caplog.records)


# ------------------------------------------------------------- compile hook


def test_compile_hook_records_into_current_run():
    from photon_ml_tpu.utils.compile_cache import install_compile_metrics_hook

    if not install_compile_metrics_hook():
        pytest.skip("jax monitoring hook unavailable in this jax build")
    try:
        from jax._src import monitoring
    except ImportError:
        pytest.skip("jax._src.monitoring unavailable")
    run = obs.RunTelemetry()
    before = obs.compile_seconds_total()
    with obs.use_run(run):
        monitoring.record_event_duration_secs("/test/obs_backend_compile", 0.25)
    assert obs.compile_seconds_total() == pytest.approx(before + 0.25)
    snap = {m["name"]: m for m in run.registry.snapshot()}
    assert snap["photon_jax_compile_total"]["value"] == 1
    assert snap["photon_jax_compile_seconds"]["sum"] == pytest.approx(0.25)


# --------------------------------------------------- zero-fetch invariant


def _small_estimator(n_cd_iterations=1):
    opt = OptimizerConfig(tolerance=1e-8, max_iterations=30)
    return GameEstimator(
        task="logistic_regression",
        coordinate_configs=[
            CoordinateConfig(
                name="global",
                feature_shard="global",
                config=GLMOptimizationConfig(
                    optimizer=opt, regularization=RegularizationContext("L2")
                ),
                reg_weights=(1.0,),
            ),
            CoordinateConfig(
                name="per-user",
                feature_shard="userShard",
                random_effect_type="userId",
                config=GLMOptimizationConfig(
                    optimizer=opt, regularization=RegularizationContext("L2")
                ),
                reg_weights=(1.0,),
            ),
        ],
        n_cd_iterations=n_cd_iterations,
        evaluator_specs=["AUC"],
        dtype=jnp.float64,
    )


@pytest.fixture(scope="module")
def game_fit_data():
    full = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=800, d_fixed=5, re_specs={"userId": (16, 4)}, seed=11
        )
    )
    return full.subset(np.arange(600)), full.subset(np.arange(600, 800))


def test_no_sink_means_no_tracker_fetch(game_fit_data, monkeypatch):
    """Lazy-aggregate invariant: with no telemetry sink registered and INFO
    logging off, the CD loop must never force the RE tracker's device
    fetch. A booby-trapped ``_aggregates`` proves it is not called."""
    from photon_ml_tpu.optimize.trackers import RandomEffectOptimizationTracker

    def _boom(self):
        raise AssertionError("device fetch inside CD hot loop with no sink")

    monkeypatch.setattr(RandomEffectOptimizationTracker, "_aggregates", _boom)
    train, val = game_fit_data
    logger = logging.getLogger("photon_ml_tpu")
    old = logger.level
    logger.setLevel(logging.WARNING)
    try:
        assert not obs.active()
        results = _small_estimator().fit(train, validation=val)
    finally:
        logger.setLevel(old)
    assert results[0].evaluation.metrics["AUC"] > 0.6


def test_active_sink_records_cd_metrics(game_fit_data):
    train, val = game_fit_data
    run = obs.RunTelemetry()
    run.register_listener(_Collector())
    with obs.use_run(run):
        _small_estimator().fit(train, validation=val)
        snap = {
            (m["name"], m["labels"].get("coordinate")): m
            for m in run.registry.snapshot()
        }
    per_user = snap[("photon_cd_iterations", "per-user")]
    assert per_user["stat"]["count"] >= 1
    assert ("photon_cd_iterations", "global") in snap
    reasons = [
        m
        for (name, _), m in snap.items()
        if name == "photon_cd_convergence_reason_total"
    ]
    assert reasons and all(m["value"] >= 1 for m in reasons)


# ------------------------------------------------------------ _DaemonFuture


def test_daemon_future_result_and_error():
    from photon_ml_tpu.cli.train import _DaemonFuture

    f = _DaemonFuture(lambda: 42)
    assert f.result(timeout=30) == 42
    assert f.done()

    def _bad():
        raise ValueError("decode failed")

    g = _DaemonFuture(_bad)
    with pytest.raises(ValueError, match="decode failed"):
        g.result(timeout=30)


def test_daemon_future_thread_is_daemon():
    from photon_ml_tpu.cli.train import _DaemonFuture

    gate = threading.Event()
    f = _DaemonFuture(gate.wait)  # blocks until released
    assert f._thread.daemon  # must not pin interpreter exit
    assert not f.done()
    gate.set()
    f.result(timeout=30)


# ------------------------------------------------- cli.train --metrics-out


@pytest.mark.slow
def test_cli_metrics_out_integration(tmp_path):
    from photon_ml_tpu.cli.train import run as train_run
    from photon_ml_tpu.io import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing.generators import generate_game_records

    data = generate_mixed_effect_data(
        n=400, d_fixed=5, re_specs={"userId": (16, 4)}, seed=4
    )
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    train_path = str(tmp_path / "train.avro")
    write_avro_file(train_path, schema, generate_game_records(data))
    mdir = str(tmp_path / "metrics")
    n_sweeps = 2
    summary = train_run(
        [
            "--input-data", train_path,
            "--validation-data", train_path,
            "--task", "logistic_regression",
            "--feature-shard", "name=global,bags=features",
            "--feature-shard", "name=userShard,bags=userFeatures",
            "--coordinate",
            "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1",
            "--coordinate",
            "name=per-user,shard=userShard,re.type=userId,reg.type=L2,reg.weights=1",
            "--evaluators", "AUC",
            "--coordinate-descent-iterations", str(n_sweeps),
            "--output-dir", str(tmp_path / "out"),
            "--metrics-out", mdir,
        ]
    )
    assert summary["best"]["metrics"]["AUC"] > 0.6

    # metrics.jsonl: every line parses; >=1 span per coordinate per sweep
    # whose parent is a cd.sweep span
    lines = [json.loads(l) for l in open(os.path.join(mdir, "metrics.jsonl"))]
    spans = [l for l in lines if l["type"] == "span"]
    sweep_ids = {s["span_id"] for s in spans if s["name"] == "cd.sweep"}
    assert len(sweep_ids) == n_sweeps
    coord_spans = [s for s in spans if s["name"] == "cd.coordinate"]
    seen = {(s["attrs"]["iteration"], s["attrs"]["coordinate"]) for s in coord_spans}
    assert seen == {
        (it, c) for it in range(n_sweeps) for c in ("global", "per-user")
    }
    assert all(s["parent_id"] in sweep_ids for s in coord_spans)
    assert sum(1 for l in lines if l["type"] == "metrics") >= n_sweeps

    # metrics.prom: prometheus exposition present and non-trivial
    prom = open(os.path.join(mdir, "metrics.prom")).read()
    assert "photon_cd_iterations" in prom
    assert "photon_solver_iterations" in prom

    # run_summary.json: wall clock, per-coordinate iteration StatCounters,
    # convergence-reason histogram
    rs = json.load(open(os.path.join(mdir, "run_summary.json")))
    assert rs["total_wall_seconds"] > 0
    assert set(rs["coordinates"]) == {"global", "per-user"}
    for coord in rs["coordinates"].values():
        assert coord["iterations"]["count"] >= 1
        assert coord["convergence_reasons"]
        assert sum(coord["convergence_reasons"].values()) >= n_sweeps
    assert rs["best"]["metrics"]["AUC"] == summary["best"]["metrics"]["AUC"]

    # bench.py reads the summary instead of scraping stdout
    import bench

    line = bench.summary_metric(os.path.join(mdir, "run_summary.json"))
    assert line["metric"] == "train_run_total_wall_seconds"
    assert line["value"] == pytest.approx(rs["total_wall_seconds"], abs=0.001)
