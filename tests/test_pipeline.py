"""Sweep pipelining (game/pipeline.py + utils/futures.PrefetchQueue): depth
>= 2 must be a pure latency optimization. Accepted models, the accept/reject
ledger, the evaluation ledger, and checkpoint boundary states are pinned
BIT-identical to the serial depth-1 loop — including under an injected NaN
storm and a kill-and-resume across a pipelined boundary."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.evaluation import build_suite
from photon_ml_tpu.game import (
    CoordinateDescent,
    FixedEffectCoordinate,
    GLMOptimizationConfig,
    RandomEffectCoordinate,
    ValidationContext,
    build_fixed_effect_dataset,
    build_random_effect_dataset,
    pipeline,
)
from photon_ml_tpu.obs import interval_overlap_seconds, overlap_ratio
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig, OptimizerType
from photon_ml_tpu.robust import CheckpointManager, SimulatedKill, faults
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset
from photon_ml_tpu.utils.futures import PrefetchQueue, WorkerPool


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.clear()


@pytest.fixture
def run():
    r = obs.RunTelemetry()
    with obs.use_run(r):
        yield r


# ------------------------------------------------------------ PrefetchQueue


def test_prefetch_queue_orders_and_exhausts():
    q = PrefetchQueue(lambda i: i * i, count=5, depth=2)
    assert [q.get() for _ in range(5)] == [(i, i * i) for i in range(5)]
    with pytest.raises(RuntimeError, match="exhausted"):
        q.get()
    q.close()
    with pytest.raises(RuntimeError, match="closed"):
        q.get()


def test_prefetch_queue_budget_bounds_inflight():
    """Byte-budgeted staging never exceeds the serial double buffer's
    2-resident worst case (held item + one staged), even at depth 4."""
    q = PrefetchQueue(
        lambda i: i, count=8, depth=4, cost=lambda i: 10, budget=15
    )
    assert [q.get()[0] for _ in range(8)] == list(range(8))
    # queue-empty always admits one item (progress guarantee), so the peak
    # is held + one staged = 20 — never depth * cost = 40
    assert q.peak_inflight <= 20
    q.close()


def test_prefetch_queue_deep_when_budget_allows():
    q = PrefetchQueue(
        lambda i: i, count=6, depth=3, cost=lambda i: 10, budget=1000
    )
    time.sleep(0.05)  # let the worker run ahead
    assert q.qsize() == 3  # bounded by depth, not budget
    assert [q.get()[0] for _ in range(6)] == list(range(6))
    q.close()


def test_prefetch_queue_cyclic_wraps():
    q = PrefetchQueue(lambda i: i, count=3, depth=2, cyclic=True)
    assert [q.get()[0] for _ in range(7)] == [0, 1, 2, 0, 1, 2, 0]
    q.close()


def test_prefetch_queue_reraises_producer_error_in_order():
    def produce(i):
        if i == 2:
            raise ValueError("boom at 2")
        return i

    q = PrefetchQueue(produce, count=5, depth=2)
    assert q.get() == (0, 0)
    assert q.get() == (1, 1)
    with pytest.raises(ValueError, match="boom at 2"):
        q.get()
    with pytest.raises(RuntimeError, match="closed"):
        q.get()


def test_prefetch_queue_validates_args():
    with pytest.raises(ValueError, match="depth"):
        PrefetchQueue(lambda i: i, count=1, depth=0)
    with pytest.raises(ValueError, match="count"):
        PrefetchQueue(lambda i: i, count=0)
    with pytest.raises(ValueError, match="workers"):
        PrefetchQueue(lambda i: i, count=1, workers=0)


# ------------------------------------------------- WorkerPool / pooled queue


def test_worker_pool_futures_and_drain_on_close():
    pool = WorkerPool(2, name="t-pool")
    futs = [pool.submit(lambda k=k: k * k) for k in range(5)]
    pool.close()  # stop accepting; already-queued tasks still drain
    assert [f.result(timeout=5) for f in futs] == [0, 1, 4, 9, 16]
    with pytest.raises(RuntimeError, match="closed"):
        pool.submit(lambda: None)

    def _boom():
        raise ValueError("pool boom")

    err_pool = WorkerPool(1)
    f = err_pool.submit(_boom)
    assert f.done() or not f.done()  # done() never raises
    with pytest.raises(ValueError, match="pool boom"):
        f.result(timeout=5)
    err_pool.close()
    with pytest.raises(ValueError, match="pool size"):
        WorkerPool(0)


def test_prefetch_queue_pooled_emits_in_order():
    """N workers decode concurrently; the sequencer re-emits results in
    production order — identical output to the single-worker queue even
    when later items finish first."""

    def produce(i):
        time.sleep(0.002 * ((7 - i) % 4))  # later items often finish first
        return i * 7

    q = PrefetchQueue(produce, count=12, depth=6, workers=4)
    assert [q.get() for _ in range(12)] == [(i, i * 7) for i in range(12)]
    with pytest.raises(RuntimeError, match="exhausted"):
        q.get()
    q.close()


def test_prefetch_queue_pooled_error_reraises_in_order():
    """A mid-sequence producer error re-raises at ITS turn: earlier items
    still emit, later items (possibly already decoded on other workers)
    are discarded, never emitted past the error."""

    def produce(i):
        if i == 3:
            raise ValueError("boom at 3")
        time.sleep(0.002 * (8 - i))
        return i

    q = PrefetchQueue(produce, count=8, depth=8, workers=4)
    assert [q.get()[1] for _ in range(3)] == [0, 1, 2]
    with pytest.raises(ValueError, match="boom at 3"):
        q.get()
    with pytest.raises(RuntimeError, match="closed"):
        q.get()


def test_prefetch_queue_budget_counts_producing_items():
    """Satellite pin: the budget charges an item when its index is CLAIMED
    (before produce starts), not when it lands in the queue — so N workers
    cannot collectively overshoot a bounded-RSS cap by starting N decodes
    at once. With cost=10 and budget=15, at most ONE produce may ever be in
    flight (the empty-pipeline progress admission), whatever the worker
    count, and the peak stays at the 2-resident worst case."""
    lock = threading.Lock()
    active = 0
    peak_active = 0

    def produce(i):
        nonlocal active, peak_active
        with lock:
            active += 1
            peak_active = max(peak_active, active)
        time.sleep(0.01)
        with lock:
            active -= 1
        return i

    q = PrefetchQueue(
        produce, count=6, depth=4, cost=lambda i: 10, budget=15, workers=3
    )
    assert [q.get()[0] for _ in range(6)] == list(range(6))
    assert peak_active == 1  # producing items count toward the budget
    assert q.peak_inflight <= 20  # held + the one in flight
    assert q.budget_stalls > 0  # the deferred admissions were observed
    q.close()


def test_prefetch_queue_shared_pool_not_closed():
    """A queue built on an externally-owned pool must not close it."""
    pool = WorkerPool(2, name="t-shared")
    q = PrefetchQueue(lambda i: i, count=4, depth=2, pool=pool)
    assert [q.get()[0] for _ in range(4)] == list(range(4))
    q.close()
    f = pool.submit(lambda: 11)  # still accepting: close() was the queue's
    assert f.result(timeout=5) == 11
    pool.close()


# ----------------------------------------------------------------- EvalLane


def test_eval_lane_drains_in_submit_order():
    def fn(snapshot):
        # later submissions finish their "work" faster — order must hold
        time.sleep(0.02 / (snapshot["k"] + 1))
        return snapshot["k"] * 10

    lane = pipeline.EvalLane(fn, capacity=3)
    for k in range(3):
        lane.submit(0, f"c{k}", {"k": k})
    out = lane.drain_all()
    assert out == [(0, "c0", 0), (0, "c1", 10), (0, "c2", 20)]
    lane.close()


def test_eval_lane_reraises_worker_error_at_drain():
    def fn(snapshot):
        if snapshot["k"] == 1:
            raise RuntimeError("eval exploded")
        return snapshot["k"]

    lane = pipeline.EvalLane(fn, capacity=2)
    lane.submit(0, "a", {"k": 0})
    lane.submit(0, "b", {"k": 1})
    with pytest.raises(RuntimeError, match="eval exploded"):
        lane.drain_all()
    lane.close()


def test_eval_lane_validates_capacity():
    with pytest.raises(ValueError, match="capacity"):
        pipeline.EvalLane(lambda s: s, capacity=0)


# ------------------------------------------------------- context plumbing


def test_pipelined_context_scopes_depth():
    assert pipeline.active_depth() == 1
    assert pipeline.stage_anchor() is None
    with pipeline.pipelined(3):
        assert pipeline.active_depth() == 3
        with pipeline.pipelined(2):
            assert pipeline.active_depth() == 2
        assert pipeline.active_depth() == 3
    assert pipeline.active_depth() == 1
    with pytest.raises(ValueError, match="depth"):
        with pipeline.pipelined(0):
            pass


def test_pipelined_context_carries_anchor():
    with obs.span("cd.sweep", iteration=0) as sweep:
        with pipeline.pipelined(2, anchor=sweep):
            assert pipeline.stage_anchor() is sweep


# -------------------------------------------------------- overlap helpers


def test_interval_overlap_seconds():
    assert interval_overlap_seconds([(0.0, 1.0)], [(2.0, 3.0)]) == 0.0
    assert interval_overlap_seconds([(0.0, 2.0)], [(1.0, 3.0)]) == pytest.approx(1.0)
    # touching endpoints merge in the union -> zero genuine overlap
    assert interval_overlap_seconds([(0.0, 1.0)], [(1.0, 2.0)]) == 0.0
    assert interval_overlap_seconds([], [(0.0, 1.0)]) == 0.0


def test_overlap_ratio():
    assert overlap_ratio([], [(0.0, 1.0)]) == 0.0
    assert overlap_ratio([(0.0, 2.0)], [(1.0, 3.0)]) == pytest.approx(0.5)
    assert overlap_ratio([(0.0, 1.0)], [(0.0, 1.0)]) == pytest.approx(1.0)
    # serial double buffer: stage strictly precedes collect
    assert overlap_ratio([(0.0, 1.0)], [(1.0, 2.0)]) == 0.0


# ------------------------------------------------- bench diff direction


def test_bench_diff_overlap_is_higher_is_better():
    import bench

    assert not bench._lower_is_better("quadrants.stream.overlap_ratio")
    row = bench._diff_one("quadrants.stream.overlap_ratio", 0.5, 0.2, 0.1)
    assert row["direction"] == "higher_is_better"
    assert row["regressed"]  # overlap DROPPING is the regression
    row = bench._diff_one("quadrants.stream.overlap_ratio", 0.004, 0.5, 0.1)
    assert not row["regressed"]


# ----------------------------------------------- CD depth-2 bit identity


def _cfg(l2=1.0):
    return GLMOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer_type=OptimizerType("LBFGS"), tolerance=1e-9, max_iterations=100
        ),
        regularization=RegularizationContext("L2"),
        reg_weight=l2,
    )


@pytest.fixture(scope="module")
def cd_factory():
    data = generate_mixed_effect_data(
        n=400, d_fixed=5, re_specs={"userId": (12, 3)}, seed=3
    )
    raw = mixed_data_to_raw_dataset(data)

    def make():
        fe_ds = build_fixed_effect_dataset(raw, "global", "global", dtype=jnp.float64)
        re_ds = build_random_effect_dataset(
            raw, "per-user", "userShard", "userId", dtype=jnp.float64
        )
        coords = {
            "global": FixedEffectCoordinate(
                dataset=fe_ds, task="logistic_regression", config=_cfg()
            ),
            "per-user": RandomEffectCoordinate(
                dataset=re_ds, task="logistic_regression", config=_cfg()
            ),
        }
        validation = ValidationContext(
            suite=build_suite(["LOGISTIC_LOSS"], raw.labels),
            score_fns={n: coords[n].score for n in coords},
            offsets=raw.offsets,
        )
        return coords, validation

    return make


def _final_score_bits(coords, result):
    return {
        name: np.asarray(coords[name].score(result.model[name]))
        for name in coords
    }


def _assert_bit_identical(coords, ref, other):
    bits_ref = _final_score_bits(coords, ref)
    bits_other = _final_score_bits(coords, other)
    for name in coords:
        np.testing.assert_array_equal(bits_ref[name], bits_other[name])
    assert [n for n, _ in ref.evaluations] == [n for n, _ in other.evaluations]
    for (_, r1), (_, r2) in zip(ref.evaluations, other.evaluations):
        assert r1.primary_metric == r2.primary_metric


def test_depth2_bit_identical_models_and_ledger(cd_factory):
    """The tentpole guarantee: depth 2 produces the exact bits of depth 1 —
    final per-coordinate scores, the evaluation ledger, and the best
    evaluation — while evals ran on a background lane."""
    coords1, val1 = cd_factory()
    ref = CoordinateDescent(coords1, n_iterations=2, validation=val1).run()
    coords2, val2 = cd_factory()
    piped = CoordinateDescent(
        coords2, n_iterations=2, validation=val2, pipeline_depth=2
    ).run()
    _assert_bit_identical(coords1, ref, piped)
    assert ref.best_evaluation.primary_metric == piped.best_evaluation.primary_metric


def test_depth2_eval_lane_runs_off_main_thread(cd_factory, run):
    """The overlap is real, not cosmetic: at depth 2 the cd.eval spans run on
    the eval-lane worker thread, parented on the sweep span (so the timeline
    attributes them as outermost phase spans)."""
    from photon_ml_tpu.obs.timeline import TimelineRecorder

    rec = TimelineRecorder()
    run.register_listener(rec)
    coords, val = cd_factory()
    CoordinateDescent(
        coords, n_iterations=2, validation=val, pipeline_depth=2
    ).run()
    evals = [s for s in rec.spans() if s.name == "cd.eval"]
    assert evals, "no cd.eval spans recorded"
    assert all(s.thread_name.startswith("photon-eval") for s in evals)
    main = threading.main_thread().ident
    assert all(s.thread_id != main for s in evals)
    # parented on the sweep span -> outermost phase spans for attribution
    sweeps = {s.span_id for s in rec.spans() if s.name == "cd.sweep"}
    assert all(s.parent_id in sweeps for s in evals)


def test_depth_validation():
    with pytest.raises(ValueError, match="pipeline_depth"):
        CoordinateDescent({"x": object()}, pipeline_depth=0)


def test_depth2_nan_storm_ledger_matches(cd_factory):
    """Injected NaN storm (2 consecutive corrupted score updates): the
    rejection counters and final bits match depth 1 exactly — the
    speculative summed-score dispatch never leaks a rejected candidate."""
    results = {}
    rejections = {}
    for depth in (1, 2):
        coords, val = cd_factory()
        r = obs.RunTelemetry()
        faults.configure("coordinate.scores:nan:1x2")
        with obs.use_run(r):
            results[depth] = CoordinateDescent(
                coords, n_iterations=2, validation=val, pipeline_depth=depth
            ).run()
        faults.clear()
        rejections[depth] = {
            name: r.registry.counter(
                "photon_coordinate_rejections_total", ""
            ).labels(coordinate=name).value
            for name in coords
        }
        results[f"coords{depth}"] = coords
    assert rejections[1] == rejections[2]
    assert sum(rejections[1].values()) == 2
    _assert_bit_identical(results["coords1"], results[1], results[2])


def test_depth2_boundary_states_match_serial(cd_factory, tmp_path):
    """Checkpoint manifests across a pipelined sweep: the boundary states a
    depth-2 run hands to the checkpointer carry the same models, summed
    scores, evaluation ledger, and train losses as depth 1 (the eval lane
    drains before every boundary)."""
    snaps = {}
    for depth in (1, 2):
        coords, val = cd_factory()
        mgr = CheckpointManager(str(tmp_path / f"d{depth}"), keep_last=10, fsync=False)
        CoordinateDescent(
            coords, n_iterations=2, validation=val,
            boundary_fn=mgr.on_boundary, pipeline_depth=depth,
        ).run()
        assert len(mgr.checkpoints()) == 4  # 2 sweeps x 2 coordinates
        snaps[depth] = mgr.latest_valid(
            expect_coordinate_order=list(coords), expect_n_iterations=2
        )
    s1, s2 = snaps[1], snaps[2]
    assert (s1.iteration, s1.coordinate_index) == (s2.iteration, s2.coordinate_index)
    np.testing.assert_array_equal(
        np.asarray(s1.summed_scores), np.asarray(s2.summed_scores)
    )
    assert [n for n, _ in s1.evaluations] == [n for n, _ in s2.evaluations]
    for (_, r1), (_, r2) in zip(s1.evaluations, s2.evaluations):
        assert r1.primary_metric == r2.primary_metric
    assert s1.train_losses == s2.train_losses


def test_depth2_kill_and_resume_across_pipelined_boundary(cd_factory, tmp_path):
    """Kill the process right after the 2nd boundary save of a DEPTH-2 run
    (mid-sweep, with an eval potentially in flight), resume at depth 2, and
    the result matches the uninterrupted depth-1 run bit-for-bit."""
    coords, val = cd_factory()
    ref = CoordinateDescent(coords, n_iterations=2, validation=val).run()

    ckpt_dir = str(tmp_path / "ck")
    coords2, val2 = cd_factory()
    mgr = CheckpointManager(ckpt_dir, fsync=False)
    faults.configure("cd.boundary_saved:kill:2")
    with pytest.raises(SimulatedKill):
        CoordinateDescent(
            coords2, n_iterations=2, validation=val2,
            boundary_fn=mgr.on_boundary, pipeline_depth=2,
        ).run()
    faults.clear()

    snap = CheckpointManager(ckpt_dir, fsync=False).latest_valid(
        expect_coordinate_order=list(coords2), expect_n_iterations=2
    )
    assert snap is not None
    assert (snap.iteration, snap.coordinate_index) == (0, 1)
    coords3, val3 = cd_factory()
    resumed = CoordinateDescent(
        coords3, n_iterations=2, validation=val3,
        resume_state=snap, pipeline_depth=2,
    ).run()
    _assert_bit_identical(coords, ref, resumed)
