"""Legacy GLM driver end-to-end tests on reference fixtures (the
DriverIntegTest role): staged pipeline, lambda grid, constraints, validators."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli import glm as glm_driver
from photon_ml_tpu.io.validators import DataValidationError, validate_dataset
from photon_ml_tpu.io.data import RawDataset

HEART = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart.avro"
HEART_VAL = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart_validation.avro"
HEART_TXT = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart_validation.txt"

needs_fixture = pytest.mark.skipif(
    not os.path.exists(HEART), reason="reference fixtures not mounted"
)


@needs_fixture
def test_legacy_driver_avro(tmp_path):
    out = str(tmp_path / "out")
    summary = glm_driver.run(
        [
            "--input-data", HEART,
            "--validation-data", HEART_VAL,
            "--task", "logistic_regression",
            "--optimizer", "TRON",
            "--max-iterations", "30",
            "--regularization-type", "L2",
            "--regularization-weights", "0.1|1|10",
            "--normalization", "STANDARDIZATION",
            "--evaluators", "AUC",
            "--output-dir", out,
        ]
    )
    assert summary["stage"] == "VALIDATED"
    assert len(summary["models"]) == 3
    aucs = [m["metrics"]["AUC"] for m in summary["models"]]
    assert max(aucs) > 0.8
    # best model files exist (text + avro)
    best = summary["best_reg_weight"]
    assert os.path.exists(os.path.join(out, f"lambda-{best}", "model.txt"))
    assert os.path.exists(os.path.join(out, f"lambda-{best}", "model.avro"))
    with open(os.path.join(out, f"lambda-{best}", "model.txt")) as f:
        lines = f.read().strip().split("\n")
    assert len(lines) == 14  # 13 features + intercept


@needs_fixture
def test_legacy_driver_libsvm(tmp_path):
    out = str(tmp_path / "out")
    summary = glm_driver.run(
        [
            "--input-data", HEART_TXT,
            "--input-format", "LIBSVM",
            "--task", "logistic_regression",
            "--regularization-type", "L2",
            "--regularization-weights", "1",
            "--normalization", "STANDARDIZATION",
            "--output-dir", out,
        ]
    )
    assert summary["stage"] == "TRAINED"
    assert summary["models"][0]["convergence_reason"] in (3, 4)


@needs_fixture
def test_legacy_driver_box_constraints(tmp_path):
    # constrain every feature into [-0.01, 0.01]
    from photon_ml_tpu.io import read_avro_dataset, FeatureShardConfig

    _, imaps = read_avro_dataset(HEART, {"global": FeatureShardConfig(("features",))})
    cmap = {k: [-0.01, 0.01] for k, _ in imaps["global"].items()}
    cpath = str(tmp_path / "constraints.json")
    with open(cpath, "w") as f:
        json.dump(cmap, f)
    out = str(tmp_path / "out")
    glm_driver.run(
        [
            "--input-data", HEART,
            "--task", "logistic_regression",
            "--optimizer", "LBFGSB",
            "--regularization-weights", "1",
            "--constraint-map", cpath,
            "--output-dir", out,
        ]
    )
    with open(os.path.join(out, "lambda-1.0", "model.txt")) as f:
        vals = [float(line.split("\t")[1]) for line in f.read().strip().split("\n")]
    assert np.all(np.abs(vals) <= 0.01 + 1e-9)


def test_validators_reject_bad_labels():
    raw = RawDataset(
        n_rows=3,
        labels=np.asarray([0.0, 2.0, 1.0]),
        offsets=np.zeros(3),
        weights=np.ones(3),
        shard_coo={"global": (np.asarray([0]), np.asarray([0]), np.asarray([1.0]))},
        shard_dims={"global": 2},
        id_tags={},
    )
    with pytest.raises(DataValidationError, match="labels outside"):
        validate_dataset(raw, "logistic_regression")
    # fine for linear regression
    validate_dataset(raw, "linear_regression")


def test_validators_reject_nonfinite_features():
    raw = RawDataset(
        n_rows=2,
        labels=np.asarray([0.0, 1.0]),
        offsets=np.zeros(2),
        weights=np.ones(2),
        shard_coo={"global": (np.asarray([0]), np.asarray([0]), np.asarray([np.nan]))},
        shard_dims={"global": 2},
        id_tags={},
    )
    with pytest.raises(DataValidationError, match="non-finite feature"):
        validate_dataset(raw, "linear_regression")


def test_validators_poisson_negative_labels():
    raw = RawDataset(
        n_rows=2,
        labels=np.asarray([-1.0, 1.0]),
        offsets=np.zeros(2),
        weights=np.ones(2),
        shard_coo={"global": (np.asarray([0]), np.asarray([0]), np.asarray([1.0]))},
        shard_dims={"global": 2},
        id_tags={},
    )
    with pytest.raises(DataValidationError, match="negative labels"):
        validate_dataset(raw, "poisson_regression")


def _raw(n=6, bad_offsets=(), bad_labels=(), bad_values=(), bad_weights=(),
         oob_cols=()):
    """A small linear-regression dataset with one feature value per row, with
    selected rows corrupted."""
    labels = np.linspace(-1.0, 1.0, n)
    offsets = np.zeros(n)
    weights = np.ones(n)
    rows = np.arange(n)
    cols = np.zeros(n, dtype=np.int64)
    vals = np.ones(n)
    labels[list(bad_labels)] = np.nan
    offsets[list(bad_offsets)] = np.inf
    weights[list(bad_weights)] = -1.0
    vals[list(bad_values)] = np.nan
    cols[list(oob_cols)] = 7
    return RawDataset(
        n_rows=n,
        labels=labels,
        offsets=offsets,
        weights=weights,
        shard_coo={"global": (rows, cols, vals)},
        shard_dims={"global": 2},
        id_tags={},
    )


def test_validators_report_offending_row_counts():
    raw = _raw(bad_offsets=(0, 3), bad_values=(2,))
    with pytest.raises(
        DataValidationError,
        match=r"2 non-finite offsets.*1 non-finite feature values across 1 rows",
    ):
        validate_dataset(raw, "linear_regression")


def test_validate_sample_threads_seed():
    """SAMPLE mode draws rows from the run seed, not a hardcoded one: a seed
    whose draw covers the bad row fails, a seed whose draw misses it passes."""
    from photon_ml_tpu.io.validators import VALIDATE_SAMPLE, _sample

    n, bad_row = 300, 17
    raw = _raw(n=n, bad_labels=(bad_row,))
    catching = missing = None
    for seed in range(200):
        hit = bad_row in _sample(n, VALIDATE_SAMPLE, seed)
        if hit and catching is None:
            catching = seed
        if not hit and missing is None:
            missing = seed
        if catching is not None and missing is not None:
            break
    assert catching is not None and missing is not None
    with pytest.raises(DataValidationError, match="non-finite labels"):
        validate_dataset(raw, "linear_regression", VALIDATE_SAMPLE,
                        rng_seed=catching)
    validate_dataset(raw, "linear_regression", VALIDATE_SAMPLE,
                     rng_seed=missing)


def test_quarantine_zero_weights_and_sanitizes():
    from photon_ml_tpu import obs
    from photon_ml_tpu.io.validators import VALIDATE_QUARANTINE

    raw = _raw(bad_labels=(0,), bad_offsets=(1,), bad_values=(2,),
               bad_weights=(3,))
    r = obs.RunTelemetry()
    with obs.use_run(r):
        count = validate_dataset(raw, "linear_regression", VALIDATE_QUARANTINE)
    assert count == 4
    assert np.all(raw.weights[:4] == 0.0) and np.all(raw.weights[4:] == 1.0)
    # quarantined rows are numerically INERT, not just weightless
    # (0 * NaN == NaN in a weighted loss)
    assert np.isfinite(raw.labels).all()
    assert np.isfinite(raw.offsets).all()
    assert np.isfinite(raw.shard_coo["global"][2]).all()
    assert (
        r.registry.counter("photon_rows_quarantined_total", "").labels().value
        == 4
    )
    # clean rows untouched
    np.testing.assert_array_equal(raw.labels[4:],
                                  np.linspace(-1.0, 1.0, 6)[4:])


def test_quarantine_rejects_all_bad_and_index_corruption():
    from photon_ml_tpu.io.validators import VALIDATE_QUARANTINE

    all_bad = _raw(n=3, bad_labels=(0, 1, 2))
    with pytest.raises(DataValidationError, match="nothing left"):
        validate_dataset(all_bad, "linear_regression", VALIDATE_QUARANTINE)

    oob = _raw(oob_cols=(1,))
    with pytest.raises(DataValidationError, match="cannot repair"):
        validate_dataset(oob, "linear_regression", VALIDATE_QUARANTINE)


def test_validators_unknown_mode_raises():
    with pytest.raises(ValueError, match="validation mode"):
        validate_dataset(_raw(), "linear_regression", "SOMETIMES")
