"""Legacy GLM driver end-to-end tests on reference fixtures (the
DriverIntegTest role): staged pipeline, lambda grid, constraints, validators."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli import glm as glm_driver
from photon_ml_tpu.io.validators import DataValidationError, validate_dataset
from photon_ml_tpu.io.data import RawDataset

HEART = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart.avro"
HEART_VAL = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart_validation.avro"
HEART_TXT = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart_validation.txt"

needs_fixture = pytest.mark.skipif(
    not os.path.exists(HEART), reason="reference fixtures not mounted"
)


@needs_fixture
def test_legacy_driver_avro(tmp_path):
    out = str(tmp_path / "out")
    summary = glm_driver.run(
        [
            "--input-data", HEART,
            "--validation-data", HEART_VAL,
            "--task", "logistic_regression",
            "--optimizer", "TRON",
            "--max-iterations", "30",
            "--regularization-type", "L2",
            "--regularization-weights", "0.1|1|10",
            "--normalization", "STANDARDIZATION",
            "--evaluators", "AUC",
            "--output-dir", out,
        ]
    )
    assert summary["stage"] == "VALIDATED"
    assert len(summary["models"]) == 3
    aucs = [m["metrics"]["AUC"] for m in summary["models"]]
    assert max(aucs) > 0.8
    # best model files exist (text + avro)
    best = summary["best_reg_weight"]
    assert os.path.exists(os.path.join(out, f"lambda-{best}", "model.txt"))
    assert os.path.exists(os.path.join(out, f"lambda-{best}", "model.avro"))
    with open(os.path.join(out, f"lambda-{best}", "model.txt")) as f:
        lines = f.read().strip().split("\n")
    assert len(lines) == 14  # 13 features + intercept


@needs_fixture
def test_legacy_driver_libsvm(tmp_path):
    out = str(tmp_path / "out")
    summary = glm_driver.run(
        [
            "--input-data", HEART_TXT,
            "--input-format", "LIBSVM",
            "--task", "logistic_regression",
            "--regularization-type", "L2",
            "--regularization-weights", "1",
            "--normalization", "STANDARDIZATION",
            "--output-dir", out,
        ]
    )
    assert summary["stage"] == "TRAINED"
    assert summary["models"][0]["convergence_reason"] in (3, 4)


@needs_fixture
def test_legacy_driver_box_constraints(tmp_path):
    # constrain every feature into [-0.01, 0.01]
    from photon_ml_tpu.io import read_avro_dataset, FeatureShardConfig

    _, imaps = read_avro_dataset(HEART, {"global": FeatureShardConfig(("features",))})
    cmap = {k: [-0.01, 0.01] for k, _ in imaps["global"].items()}
    cpath = str(tmp_path / "constraints.json")
    with open(cpath, "w") as f:
        json.dump(cmap, f)
    out = str(tmp_path / "out")
    glm_driver.run(
        [
            "--input-data", HEART,
            "--task", "logistic_regression",
            "--optimizer", "LBFGSB",
            "--regularization-weights", "1",
            "--constraint-map", cpath,
            "--output-dir", out,
        ]
    )
    with open(os.path.join(out, "lambda-1.0", "model.txt")) as f:
        vals = [float(line.split("\t")[1]) for line in f.read().strip().split("\n")]
    assert np.all(np.abs(vals) <= 0.01 + 1e-9)


def test_validators_reject_bad_labels():
    raw = RawDataset(
        n_rows=3,
        labels=np.asarray([0.0, 2.0, 1.0]),
        offsets=np.zeros(3),
        weights=np.ones(3),
        shard_coo={"global": (np.asarray([0]), np.asarray([0]), np.asarray([1.0]))},
        shard_dims={"global": 2},
        id_tags={},
    )
    with pytest.raises(DataValidationError, match="labels outside"):
        validate_dataset(raw, "logistic_regression")
    # fine for linear regression
    validate_dataset(raw, "linear_regression")


def test_validators_reject_nonfinite_features():
    raw = RawDataset(
        n_rows=2,
        labels=np.asarray([0.0, 1.0]),
        offsets=np.zeros(2),
        weights=np.ones(2),
        shard_coo={"global": (np.asarray([0]), np.asarray([0]), np.asarray([np.nan]))},
        shard_dims={"global": 2},
        id_tags={},
    )
    with pytest.raises(DataValidationError, match="non-finite feature"):
        validate_dataset(raw, "linear_regression")


def test_validators_poisson_negative_labels():
    raw = RawDataset(
        n_rows=2,
        labels=np.asarray([-1.0, 1.0]),
        offsets=np.zeros(2),
        weights=np.ones(2),
        shard_coo={"global": (np.asarray([0]), np.asarray([0]), np.asarray([1.0]))},
        shard_dims={"global": 2},
        id_tags={},
    )
    with pytest.raises(DataValidationError, match="negative labels"):
        validate_dataset(raw, "poisson_regression")
