"""IO tests: Avro codec round-trips (incl. against real reference fixtures),
index maps, LIBSVM and Avro dataset readers, model save/load."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.io import (
    FeatureShardConfig,
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
    load_game_model,
    load_glm,
    read_avro_dataset,
    read_avro_file,
    read_libsvm,
    save_game_model,
    save_glm,
    write_avro_file,
)
from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
from photon_ml_tpu.models import Coefficients, LogisticRegressionModel
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel

REFERENCE_FIXTURES = "/root/reference/photon-client/src/integTest/resources"


def _mk_records(n=25, d=6, seed=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        nnz = rng.integers(1, d)
        cols = rng.choice(d, size=nnz, replace=False)
        recs.append(
            {
                "uid": f"uid{i}",
                "label": float(rng.integers(0, 2)),
                "features": [
                    {"name": f"f{c}", "term": "t", "value": float(rng.normal())}
                    for c in cols
                ],
                "metadataMap": {"userId": f"u{i % 5}"},
                "weight": 1.0 + float(rng.uniform()),
                "offset": float(rng.normal() * 0.1),
            }
        )
    return recs


def test_avro_round_trip(tmp_path):
    recs = _mk_records()
    p = str(tmp_path / "t.avro")
    for codec in ("null", "deflate"):
        write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs, codec=codec)
        schema, back = read_avro_file(p)
        assert back == recs
        assert schema["name"] == "TrainingExampleAvro"


@pytest.mark.skipif(
    not os.path.isdir(REFERENCE_FIXTURES), reason="reference fixtures not mounted"
)
def test_avro_reads_reference_fixtures():
    p = os.path.join(REFERENCE_FIXTURES, "DriverIntegTest/input/heart.avro")
    schema, recs = read_avro_file(p)
    assert len(recs) == 250
    assert {f["name"] for f in schema["fields"]} >= {"label", "features"}
    labels = {r["label"] for r in recs}
    assert labels <= {-1, 1, -1.0, 1.0, 0.0, 0}

    # GAME fixture with multiple feature bags + id columns
    p2 = os.path.join(
        REFERENCE_FIXTURES, "GameIntegTest/input/duplicateFeatures/yahoo-music-train.avro"
    )
    schema2, recs2 = read_avro_file(p2)
    assert {"userId", "songId", "features", "userFeatures", "songFeatures"} <= {
        f["name"] for f in schema2["fields"]
    }
    assert len(recs2) > 0


def test_index_map_round_trip(tmp_path):
    im = IndexMap.from_name_terms([("a", ""), ("b", "x"), ("c", "")])
    assert im.intercept_index == len(im) - 1
    assert im.get_index(feature_key("b", "x")) >= 0
    assert im.get_index("nope") == -1
    p = str(tmp_path / "idx.bin")
    im.save(p)
    im2 = IndexMap.load(p)
    assert dict(im.items()) == dict(im2.items())


def test_read_avro_dataset(tmp_path):
    recs = _mk_records()
    p = str(tmp_path / "train.avro")
    write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs)
    shard_cfg = {"global": FeatureShardConfig(feature_bags=("features",))}
    ds, imaps = read_avro_dataset(p, shard_cfg, id_tag_columns=["userId"])
    assert ds.n_rows == 25
    assert ds.shard_dims["global"] == len(imaps["global"])
    assert imaps["global"].intercept_index is not None
    # every row got an intercept entry
    rows, cols, vals = ds.shard_coo["global"]
    icol = imaps["global"].intercept_index
    assert np.sum(cols == icol) == 25
    assert set(ds.id_tags["userId"]) == {f"u{i}" for i in range(5)}
    # batch conversion
    batch = ds.to_batch("global", dtype=jnp.float64)
    assert batch.n_rows == 25
    np.testing.assert_allclose(np.asarray(batch.labels), ds.labels)


def test_read_libsvm(tmp_path):
    p = str(tmp_path / "data.libsvm")
    with open(p, "w") as f:
        f.write("+1 1:0.5 3:1.5\n-1 2:2.0\n+1 1:1.0 2:1.0 3:1.0\n")
    ds = read_libsvm(p)
    assert ds.n_rows == 3
    np.testing.assert_allclose(ds.labels, [1, 0, 1])
    batch = ds.to_batch("global", dtype=jnp.float64)
    x = np.asarray(batch.features.to_dense())
    # cols 1..3 populated, last col is intercept
    np.testing.assert_allclose(x[0], [0, 0.5, 0, 1.5, 1.0])


def test_glm_save_load(tmp_path):
    im = IndexMap.from_name_terms([("f0", ""), ("f1", "t")])
    means = jnp.asarray([0.5, -1.5, 2.0], jnp.float64)
    model = LogisticRegressionModel(Coefficients(means=means))
    p = str(tmp_path / "model" / "part-00000.avro")
    save_glm(p, model, im, model_id="m1")
    back = load_glm(p, im)
    assert isinstance(back, LogisticRegressionModel)
    np.testing.assert_allclose(np.asarray(back.coefficients.means), np.asarray(means))


def test_game_model_save_load(tmp_path):
    im_f = IndexMap.from_name_terms([("g0", ""), ("g1", "")])
    im_u = IndexMap.from_name_terms([("u0", ""), ("u1", "")])
    fe = FixedEffectModel(
        model=LogisticRegressionModel(Coefficients(jnp.asarray([1.0, -2.0, 0.5], jnp.float64))),
        feature_shard="globalShard",
    )
    re = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=np.asarray(["uA", "uB"], dtype=object),
        coef_indices=jnp.asarray([[0, 2], [1, -1]], jnp.int32),
        coef_values=jnp.asarray([[0.3, -0.7], [1.1, 0.0]], jnp.float64),
    )
    gm = GameModel(models={"global": fe, "per-user": re}, task="logistic_regression")
    d = str(tmp_path / "gameModel")
    imaps = {"globalShard": im_f, "userShard": im_u}
    save_game_model(d, gm, imaps)

    assert os.path.exists(os.path.join(d, "model-metadata.json"))
    assert open(os.path.join(d, "fixed-effect", "global", "id-info")).read().strip() == "globalShard"

    back = load_game_model(d, imaps)
    assert set(back.coordinates()) == {"global", "per-user"}
    np.testing.assert_allclose(
        np.asarray(back["global"].model.coefficients.means), [1.0, -2.0, 0.5]
    )
    re2 = back["per-user"]
    assert re2.random_effect_type == "userId"
    dense = re2.dense_coefficients(3)
    exp = np.zeros((2, 3))
    exp[0, 0], exp[0, 2] = 0.3, -0.7
    exp[1, 1] = 1.1
    rows = re2.rows_for(["uA", "uB"])
    np.testing.assert_allclose(dense[rows], exp)
    assert re2.entity_row("unseen") == -1


def test_random_effect_scoring_unseen_entity():
    re = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="s",
        task="linear_regression",
        entity_ids=np.asarray(["a"], dtype=object),
        coef_indices=jnp.asarray([[0, 3]], jnp.int32),
        coef_values=jnp.asarray([[2.0, 10.0]], jnp.float64),
    )
    # two rows: entity a, and unseen (-1)
    rows = jnp.asarray([0, -1])
    fi = jnp.asarray([[0, 3], [0, 3]], jnp.int32)
    fv = jnp.asarray([[1.0, 0.5], [1.0, 0.5]], jnp.float64)
    s = np.asarray(re.score_ell_rows(rows, fi, fv))
    np.testing.assert_allclose(s, [2.0 + 5.0, 0.0])


# --- reader robustness: schema resolution, column remap, date ranges ---


def test_avro_schema_resolution_evolved(tmp_path):
    """Writer schema evolves: reader gains a defaulted field, loses a writer
    field, promotes int->double (Avro spec resolution; io/avro.py)."""
    from photon_ml_tpu.io import read_avro_file, write_avro_file

    writer_schema = {
        "type": "record",
        "name": "Row",
        "fields": [
            {"name": "label", "type": "int"},
            {"name": "legacy", "type": "string"},
            {"name": "weight", "type": ["null", "float"], "default": None},
        ],
    }
    recs = [
        {"label": 1, "legacy": "drop-me", "weight": 2.5},
        {"label": 0, "legacy": "x", "weight": None},
    ]
    p = str(tmp_path / "old.avro")
    write_avro_file(p, writer_schema, recs)

    reader_schema = {
        "type": "record",
        "name": "Row",
        "fields": [
            {"name": "label", "type": "double"},  # int -> double promotion
            {"name": "weight", "type": ["null", "float"], "default": None},
            {"name": "offset", "type": "double", "default": 0.25},  # new field
        ],
    }
    _, out = read_avro_file(p, reader_schema=reader_schema)
    assert out[0] == {"label": 1.0, "weight": 2.5, "offset": 0.25}
    assert isinstance(out[0]["label"], float)
    assert out[1] == {"label": 0.0, "weight": None, "offset": 0.25}
    assert "legacy" not in out[0]  # writer-only field skipped

    # without a reader schema the writer shape comes back unchanged
    _, raw = read_avro_file(p)
    assert raw[0]["legacy"] == "drop-me"

    # a reader field with no default and no writer data is an error
    bad_reader = {
        "type": "record",
        "name": "Row",
        "fields": [{"name": "brand_new", "type": "double"}],
    }
    with pytest.raises(ValueError, match="no default"):
        read_avro_file(p, reader_schema=bad_reader)


def test_avro_schema_resolution_nested_and_union(tmp_path):
    """Resolution recurses through arrays/records and across union shapes."""
    from photon_ml_tpu.io import read_avro_file, write_avro_file

    writer_schema = {
        "type": "record",
        "name": "Example",
        "fields": [
            {
                "name": "features",
                "type": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "Feat",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "value", "type": "float"},
                        ],
                    },
                },
            },
            {"name": "response", "type": "int"},
        ],
    }
    recs = [{"features": [{"name": "a", "value": 1.5}], "response": 1}]
    p = str(tmp_path / "nested.avro")
    write_avro_file(p, writer_schema, recs)

    reader_schema = {
        "type": "record",
        "name": "Example",
        "fields": [
            {
                "name": "features",
                "type": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "Feat",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "value", "type": "double"},
                            {"name": "term", "type": "string", "default": ""},
                        ],
                    },
                },
            },
            # writer non-union int resolves into reader union double
            {"name": "response", "type": ["null", "double"]},
        ],
    }
    _, out = read_avro_file(p, reader_schema=reader_schema)
    assert out[0]["features"] == [{"name": "a", "value": 1.5, "term": ""}]
    assert out[0]["response"] == 1.0


def test_input_columns_remap(tmp_path):
    """Reserved columns read under user-remapped names
    (InputColumnsNames.scala:29-106)."""
    from photon_ml_tpu.io import (
        FeatureShardConfig,
        InputColumnsNames,
        read_avro_dataset,
        write_avro_file,
    )

    schema = {
        "type": "record",
        "name": "Custom",
        "fields": [
            {"name": "target", "type": "double"},
            {"name": "importance", "type": "double"},
            {"name": "baseline", "type": "double"},
            {"name": "rowId", "type": "string"},
            {"name": "tags", "type": {"type": "map", "values": "string"}},
            {
                "name": "features",
                "type": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "FeatureAvro",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": "string"},
                            {"name": "value", "type": "double"},
                        ],
                    },
                },
            },
        ],
    }
    recs = [
        {
            "target": 1.0, "importance": 2.0, "baseline": 0.5, "rowId": "r0",
            "tags": {"userId": "u7"},
            "features": [{"name": "f", "term": "", "value": 3.0}],
        }
    ]
    p = str(tmp_path / "custom.avro")
    write_avro_file(p, schema, recs)

    cols = InputColumnsNames.from_spec(
        "response=target,weight=importance,offset=baseline,uid=rowId,metadataMap=tags"
    )
    ds, _ = read_avro_dataset(
        p,
        {"global": FeatureShardConfig(("features",))},
        id_tag_columns=["userId"],
        response_column="target",
        columns=cols,
    )
    assert ds.labels[0] == 1.0
    assert ds.weights[0] == 2.0
    assert ds.offsets[0] == 0.5
    assert ds.uids[0] == "r0"
    assert ds.id_tags["userId"][0] == "u7"

    # duplicate names rejected (InputColumnsNames uniqueness require)
    with pytest.raises(ValueError, match="unique"):
        InputColumnsNames.from_spec("response=weight")
    with pytest.raises(ValueError, match="unknown input columns"):
        InputColumnsNames.from_spec("bogus=x")


def test_date_ranges_and_day_dirs(tmp_path):
    import datetime

    from photon_ml_tpu.utils.dates import (
        DateRange,
        DaysRange,
        input_paths_within_date_range,
    )

    rng = DateRange.from_string("20260101-20260103")
    assert [d.isoformat() for d in rng.days()] == [
        "2026-01-01", "2026-01-02", "2026-01-03",
    ]
    assert str(rng) == "20260101-20260103"
    with pytest.raises(ValueError):
        DateRange.from_string("20260105-20260101")  # start after end
    with pytest.raises(ValueError):
        DateRange.from_string("2026-01-01")  # bad format

    dr = DaysRange.from_string("3-1")
    today = datetime.date(2026, 1, 4)
    assert str(dr.to_date_range(today)) == "20260101-20260103"
    with pytest.raises(ValueError):
        DaysRange.from_string("1-3")  # start fewer days ago than end

    # day-dir layout: only existing days come back, in order
    base = tmp_path / "daily"
    for day in ("2026/01/01", "2026/01/03"):
        (base / day).mkdir(parents=True)
    paths = input_paths_within_date_range(str(base), rng)
    assert [p[-10:] for p in paths] == ["2026/01/01", "2026/01/03"]
    with pytest.raises(FileNotFoundError):
        input_paths_within_date_range(str(base), rng, error_on_missing=True)
    with pytest.raises(FileNotFoundError):
        input_paths_within_date_range(
            str(base), DateRange.from_string("20300101-20300102")
        )


def test_mmap_index_store_round_trip(tmp_path):
    """v2 key-sorted mmap stores: binary-search lookups, reverse lookup,
    iteration, and partition routing (PalDBIndexMap.scala:69-105 role)."""
    from photon_ml_tpu.io.index_map import (
        INTERCEPT_KEY,
        IndexMap,
        MmapIndexMap,
        PartitionedIndexMap,
        load_partitioned,
        save_partitioned,
    )

    keys = [f"f{i:04d}\x01t{i % 7}" for i in range(500)]
    imap = IndexMap.from_keys(keys, add_intercept=True)

    p = str(tmp_path / "store.bin")
    MmapIndexMap.write(imap.items(), p)
    mm = MmapIndexMap.open(p)
    assert len(mm) == len(imap)
    for k in [keys[0], keys[123], keys[-1], INTERCEPT_KEY]:
        assert mm.get_index(k) == imap.get_index(k)
    assert mm.get_index("nope") == -1
    assert "nope" not in mm and keys[3] in mm
    assert mm.intercept_index == imap.intercept_index
    for i in (0, 17, len(imap) - 1):
        assert mm.get_feature_name(i) == imap.get_feature_name(i)
    assert mm.get_feature_name(len(imap) + 5) is None
    assert dict(mm.items()) == dict(imap.items())

    # partitioned layout end-to-end (what cli.index writes / cli.train loads)
    out = str(tmp_path / "parts")
    save_partitioned(imap, out, num_partitions=4, shard="global")
    part = load_partitioned(out, "global")
    assert isinstance(part, PartitionedIndexMap)
    assert len(part) == len(imap)
    for k in keys[::37] + [INTERCEPT_KEY]:
        assert part.get_index(k) == imap.get_index(k)
    assert part.get_index("missing") == -1
    assert part.get_feature_name(3) == imap.get_feature_name(3)
    assert dict(part.items()) == dict(imap.items())


def test_v1_index_store_still_loads(tmp_path):
    from photon_ml_tpu.io.index_map import IndexMap, load_partitioned

    imap = IndexMap.from_keys(["a\x01", "b\x01x"], add_intercept=True)
    out = str(tmp_path / "v1")
    import json
    import os

    os.makedirs(out)
    imap.save(os.path.join(out, "index-g-00000.bin"))  # v1 single partition
    with open(os.path.join(out, "_index-g-meta.json"), "w") as f:
        json.dump({"shard": "g", "numPartitions": 1, "size": len(imap)}, f)
    loaded = load_partitioned(out, "g")
    assert dict(loaded.items()) == dict(imap.items())


# -------------------------------------------------------- chunked ingest


def _write_parts(tmp_path, n_parts=4, per_part=60):
    recs = _mk_records(n=n_parts * per_part, d=8, seed=5)
    d = tmp_path / "parts"
    d.mkdir()
    for i in range(n_parts):
        write_avro_file(
            str(d / f"part-{i:05d}.avro"),
            TRAINING_EXAMPLE_AVRO,
            recs[i * per_part : (i + 1) * per_part],
        )
    return str(d)


def _assert_same_dataset(a, b):
    assert a.n_rows == b.n_rows
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.offsets, b.offsets)
    np.testing.assert_array_equal(a.weights, b.weights)
    assert a.shard_dims == b.shard_dims
    for s in a.shard_coo:
        for x, y in zip(a.shard_coo[s], b.shard_coo[s]):
            np.testing.assert_array_equal(x, y)
    for t in a.id_tags:
        np.testing.assert_array_equal(a.id_tags[t], b.id_tags[t])
    np.testing.assert_array_equal(a.uids, b.uids)


def test_chunked_reader_matches_monolithic(tmp_path):
    """The pipelined per-part reader is bit-identical to the full-decode
    reader: same rows in the same order, same index maps, same COO triples —
    with and without prebuilt maps."""
    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    mono, maps_m = read_avro_dataset(
        path, shards, id_tag_columns=("userId",), engine="python"
    )
    chunk, maps_c = read_avro_dataset_chunked(
        path, shards, id_tag_columns=("userId",), engine="python"
    )
    _assert_same_dataset(mono, chunk)
    assert {s: dict(m.items()) for s, m in maps_m.items()} == {
        s: dict(m.items()) for s, m in maps_c.items()
    }
    # prebuilt maps skip the keys pass but land on the same dataset
    pre, _ = read_avro_dataset_chunked(
        path, shards, index_maps=maps_m, id_tag_columns=("userId",), engine="python"
    )
    _assert_same_dataset(mono, pre)
    # single part: delegates to the monolithic reader
    one, _ = read_avro_dataset_chunked(
        os.path.join(path, "part-00000.avro"), shards, index_maps=maps_m,
        engine="python",
    )
    assert one.n_rows == 60


def test_chunked_reader_bounds_peak_memory(tmp_path):
    """Acceptance: chunked ingest keeps peak host allocation below the
    full-decode path on multi-part input (the record list never fully
    materializes — residency is ~2 parts)."""
    import tracemalloc

    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path, n_parts=6, per_part=120)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    _, maps = read_avro_dataset(path, shards, engine="python")

    tracemalloc.start()
    read_avro_dataset(path, shards, index_maps=maps, engine="python")
    mono_peak = tracemalloc.get_traced_memory()[1]
    tracemalloc.stop()

    tracemalloc.start()
    read_avro_dataset_chunked(path, shards, index_maps=maps, engine="python")
    chunk_peak = tracemalloc.get_traced_memory()[1]
    tracemalloc.stop()

    assert chunk_peak < mono_peak


def test_chunked_reader_counts_parts(tmp_path):
    from photon_ml_tpu import obs
    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path, n_parts=3, per_part=20)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    run = obs.RunTelemetry()
    with obs.use_run(run):
        ds, _ = read_avro_dataset_chunked(path, shards, engine="python")
    snap = {
        (m["name"], m["labels"].get("mode")): m for m in run.registry.snapshot()
    }
    assert snap[("photon_ingest_parts_total", "chunked")]["value"] == 3
    assert snap[("photon_ingest_rows_total", "chunked")]["value"] == ds.n_rows == 60
    # the bounded prefetch queue reports its occupancy (0..depth; the last
    # get always observes an empty queue, so the final value is 0)
    assert snap[("photon_ingest_queue_depth", "chunked")]["value"] == 0


def test_chunked_reader_prefetch_depth_validated(tmp_path):
    import pytest as _pytest

    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path, n_parts=3, per_part=20)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    with _pytest.raises(ValueError, match="prefetch_depth"):
        read_avro_dataset_chunked(
            path, shards, engine="python", prefetch_depth=0
        )
    # deeper lookahead lands on the identical dataset (order is pinned)
    _, maps = read_avro_dataset(path, shards, engine="python")
    a, _ = read_avro_dataset_chunked(
        path, shards, index_maps=maps, engine="python", prefetch_depth=3
    )
    b, _ = read_avro_dataset_chunked(
        path, shards, index_maps=maps, engine="python", prefetch_depth=1
    )
    _assert_same_dataset(a, b)


# -------------------------------------------------------- pooled fleet ingest


def test_pooled_reader_bitwise_parity_any_worker_count(tmp_path):
    """Acceptance: the N-worker decode pool produces a RawDataset identical
    to the serial reader — same rows in the same order, same COO triples,
    same index maps — at every worker count (the sequencer re-emits parts
    in file order regardless of completion order)."""
    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path, n_parts=6, per_part=40)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    serial, maps = read_avro_dataset_chunked(
        path, shards, engine="python", workers=1
    )
    for workers in (2, 4, "auto"):
        pooled, maps_p = read_avro_dataset_chunked(
            path, shards, engine="python", workers=workers
        )
        _assert_same_dataset(serial, pooled)
        assert dict(maps_p["g"].items()) == dict(maps["g"].items())


def test_pooled_reader_reraises_poisoned_part_in_order(tmp_path):
    """A corrupt MIDDLE part re-raises on the consumer at that part's turn:
    earlier parts still convert, later parts (which may have decoded first
    on other workers) are discarded, never emitted out of order."""
    import pytest as _pytest

    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path, n_parts=5, per_part=20)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    _, maps = read_avro_dataset(path, shards, engine="python")
    poisoned = os.path.join(path, "part-00002.avro")
    with open(poisoned, "wb") as f:
        f.write(b"Obj\x01 this is not a valid container file")
    with _pytest.raises(Exception) as err:
        read_avro_dataset_chunked(
            path, shards, index_maps=maps, engine="python", workers=4
        )
    # the error is the decode failure, not an out-of-order sequencing error
    assert "out of order" not in str(err.value)


def test_pooled_reader_budget_backpressure_and_stall_counter(tmp_path):
    """ingest_budget_bytes composes with the pool: a budget below two
    compressed parts forces serial admission (stalls counted in
    photon_ingest_budget_stalls_total) and the output stays bit-identical."""
    from photon_ml_tpu import obs
    from photon_ml_tpu.io import read_avro_dataset_chunked

    path = _write_parts(tmp_path, n_parts=5, per_part=40)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    serial, maps = read_avro_dataset_chunked(
        path, shards, engine="python", workers=1
    )
    part = os.path.join(path, "part-00000.avro")
    budget = os.path.getsize(part) + 1  # admits ~one compressed part
    run = obs.RunTelemetry()
    with obs.use_run(run):
        pooled, _ = read_avro_dataset_chunked(
            path, shards, index_maps=maps, engine="python", workers=4,
            ingest_budget_bytes=budget,
        )
    _assert_same_dataset(serial, pooled)
    snap = {
        (m["name"], m["labels"].get("mode")): m for m in run.registry.snapshot()
    }
    assert snap[("photon_ingest_budget_stalls_total", "chunked")]["value"] > 0


def test_pooled_reader_bounded_rss_envelope(tmp_path):
    """Acceptance (bounded-RSS): peak host allocation of the pooled reader
    stays within a fixed envelope independent of part count and worker
    count — 3x more parts under the same ingest budget must not grow the
    peak by more than the envelope slack, at 1 worker and at 4. The
    obs/memory watermarks (VmHWM via sample_memory) are recorded alongside:
    the kernel high-water mark is monotone per process, so the assertion
    rides on tracemalloc while the photon_mem_* gauges prove the sampling
    hook sees the run."""
    import tracemalloc

    from photon_ml_tpu import obs
    from photon_ml_tpu.io import read_avro_dataset_chunked

    (tmp_path / "small").mkdir()
    (tmp_path / "big").mkdir()
    small = _write_parts(tmp_path / "small", n_parts=4, per_part=60)
    big = _write_parts(tmp_path / "big", n_parts=12, per_part=60)
    shards = {"g": FeatureShardConfig(feature_bags=("features",))}
    _, maps = read_avro_dataset(small, shards, engine="python")
    budget = os.path.getsize(os.path.join(small, "part-00000.avro")) * 2

    def peak(path, workers):
        run = obs.RunTelemetry()
        tracemalloc.start()
        with obs.use_run(run):
            ds, _ = read_avro_dataset_chunked(
                path, shards, index_maps=maps, engine="python",
                workers=workers, ingest_budget_bytes=budget,
            )
            host = obs.sample_memory(run.registry)
        top = tracemalloc.get_traced_memory()[1]
        tracemalloc.stop()
        assert host.get("peak_rss_bytes", 0) > 0  # watermark sampled
        # subtract the returned dataset itself: the envelope bounds the
        # TRANSIENT decode residency, the output scales with rows by design
        out_bytes = sum(
            arr.nbytes
            for arr in (ds.labels, ds.offsets, ds.weights)
        ) + sum(a.nbytes for coo in ds.shard_coo.values() for a in coo)
        return top - out_bytes

    base = peak(small, workers=1)
    for workers in (1, 4):
        grown = peak(big, workers=workers)
        # fixed envelope: 3x the parts, same transient peak within 2x slack
        assert grown < base * 2 + (1 << 20), (workers, base, grown)


def test_resolve_ingest_workers():
    import pytest as _pytest

    from photon_ml_tpu.io import resolve_ingest_workers

    auto = resolve_ingest_workers(None)
    assert auto >= 1
    assert resolve_ingest_workers("auto") == auto
    assert resolve_ingest_workers(0) == auto
    assert resolve_ingest_workers(3) == 3
    with _pytest.raises(ValueError, match="ingest workers"):
        resolve_ingest_workers(-1)
