"""IO tests: Avro codec round-trips (incl. against real reference fixtures),
index maps, LIBSVM and Avro dataset readers, model save/load."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.io import (
    FeatureShardConfig,
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
    load_game_model,
    load_glm,
    read_avro_dataset,
    read_avro_file,
    read_libsvm,
    save_game_model,
    save_glm,
    write_avro_file,
)
from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
from photon_ml_tpu.models import Coefficients, LogisticRegressionModel
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel

REFERENCE_FIXTURES = "/root/reference/photon-client/src/integTest/resources"


def _mk_records(n=25, d=6, seed=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        nnz = rng.integers(1, d)
        cols = rng.choice(d, size=nnz, replace=False)
        recs.append(
            {
                "uid": f"uid{i}",
                "label": float(rng.integers(0, 2)),
                "features": [
                    {"name": f"f{c}", "term": "t", "value": float(rng.normal())}
                    for c in cols
                ],
                "metadataMap": {"userId": f"u{i % 5}"},
                "weight": 1.0 + float(rng.uniform()),
                "offset": float(rng.normal() * 0.1),
            }
        )
    return recs


def test_avro_round_trip(tmp_path):
    recs = _mk_records()
    p = str(tmp_path / "t.avro")
    for codec in ("null", "deflate"):
        write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs, codec=codec)
        schema, back = read_avro_file(p)
        assert back == recs
        assert schema["name"] == "TrainingExampleAvro"


@pytest.mark.skipif(
    not os.path.isdir(REFERENCE_FIXTURES), reason="reference fixtures not mounted"
)
def test_avro_reads_reference_fixtures():
    p = os.path.join(REFERENCE_FIXTURES, "DriverIntegTest/input/heart.avro")
    schema, recs = read_avro_file(p)
    assert len(recs) == 250
    assert {f["name"] for f in schema["fields"]} >= {"label", "features"}
    labels = {r["label"] for r in recs}
    assert labels <= {-1, 1, -1.0, 1.0, 0.0, 0}

    # GAME fixture with multiple feature bags + id columns
    p2 = os.path.join(
        REFERENCE_FIXTURES, "GameIntegTest/input/duplicateFeatures/yahoo-music-train.avro"
    )
    schema2, recs2 = read_avro_file(p2)
    assert {"userId", "songId", "features", "userFeatures", "songFeatures"} <= {
        f["name"] for f in schema2["fields"]
    }
    assert len(recs2) > 0


def test_index_map_round_trip(tmp_path):
    im = IndexMap.from_name_terms([("a", ""), ("b", "x"), ("c", "")])
    assert im.intercept_index == len(im) - 1
    assert im.get_index(feature_key("b", "x")) >= 0
    assert im.get_index("nope") == -1
    p = str(tmp_path / "idx.bin")
    im.save(p)
    im2 = IndexMap.load(p)
    assert dict(im.items()) == dict(im2.items())


def test_read_avro_dataset(tmp_path):
    recs = _mk_records()
    p = str(tmp_path / "train.avro")
    write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs)
    shard_cfg = {"global": FeatureShardConfig(feature_bags=("features",))}
    ds, imaps = read_avro_dataset(p, shard_cfg, id_tag_columns=["userId"])
    assert ds.n_rows == 25
    assert ds.shard_dims["global"] == len(imaps["global"])
    assert imaps["global"].intercept_index is not None
    # every row got an intercept entry
    rows, cols, vals = ds.shard_coo["global"]
    icol = imaps["global"].intercept_index
    assert np.sum(cols == icol) == 25
    assert set(ds.id_tags["userId"]) == {f"u{i}" for i in range(5)}
    # batch conversion
    batch = ds.to_batch("global", dtype=jnp.float64)
    assert batch.n_rows == 25
    np.testing.assert_allclose(np.asarray(batch.labels), ds.labels)


def test_read_libsvm(tmp_path):
    p = str(tmp_path / "data.libsvm")
    with open(p, "w") as f:
        f.write("+1 1:0.5 3:1.5\n-1 2:2.0\n+1 1:1.0 2:1.0 3:1.0\n")
    ds = read_libsvm(p)
    assert ds.n_rows == 3
    np.testing.assert_allclose(ds.labels, [1, 0, 1])
    batch = ds.to_batch("global", dtype=jnp.float64)
    x = np.asarray(batch.features.to_dense())
    # cols 1..3 populated, last col is intercept
    np.testing.assert_allclose(x[0], [0, 0.5, 0, 1.5, 1.0])


def test_glm_save_load(tmp_path):
    im = IndexMap.from_name_terms([("f0", ""), ("f1", "t")])
    means = jnp.asarray([0.5, -1.5, 2.0], jnp.float64)
    model = LogisticRegressionModel(Coefficients(means=means))
    p = str(tmp_path / "model" / "part-00000.avro")
    save_glm(p, model, im, model_id="m1")
    back = load_glm(p, im)
    assert isinstance(back, LogisticRegressionModel)
    np.testing.assert_allclose(np.asarray(back.coefficients.means), np.asarray(means))


def test_game_model_save_load(tmp_path):
    im_f = IndexMap.from_name_terms([("g0", ""), ("g1", "")])
    im_u = IndexMap.from_name_terms([("u0", ""), ("u1", "")])
    fe = FixedEffectModel(
        model=LogisticRegressionModel(Coefficients(jnp.asarray([1.0, -2.0, 0.5], jnp.float64))),
        feature_shard="globalShard",
    )
    re = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=np.asarray(["uA", "uB"], dtype=object),
        coef_indices=jnp.asarray([[0, 2], [1, -1]], jnp.int32),
        coef_values=jnp.asarray([[0.3, -0.7], [1.1, 0.0]], jnp.float64),
    )
    gm = GameModel(models={"global": fe, "per-user": re}, task="logistic_regression")
    d = str(tmp_path / "gameModel")
    imaps = {"globalShard": im_f, "userShard": im_u}
    save_game_model(d, gm, imaps)

    assert os.path.exists(os.path.join(d, "model-metadata.json"))
    assert open(os.path.join(d, "fixed-effect", "global", "id-info")).read().strip() == "globalShard"

    back = load_game_model(d, imaps)
    assert set(back.coordinates()) == {"global", "per-user"}
    np.testing.assert_allclose(
        np.asarray(back["global"].model.coefficients.means), [1.0, -2.0, 0.5]
    )
    re2 = back["per-user"]
    assert re2.random_effect_type == "userId"
    dense = re2.dense_coefficients(3)
    exp = np.zeros((2, 3))
    exp[0, 0], exp[0, 2] = 0.3, -0.7
    exp[1, 1] = 1.1
    rows = re2.rows_for(["uA", "uB"])
    np.testing.assert_allclose(dense[rows], exp)
    assert re2.entity_row("unseen") == -1


def test_random_effect_scoring_unseen_entity():
    re = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="s",
        task="linear_regression",
        entity_ids=np.asarray(["a"], dtype=object),
        coef_indices=jnp.asarray([[0, 3]], jnp.int32),
        coef_values=jnp.asarray([[2.0, 10.0]], jnp.float64),
    )
    # two rows: entity a, and unseen (-1)
    rows = jnp.asarray([0, -1])
    fi = jnp.asarray([[0, 3], [0, 3]], jnp.int32)
    fv = jnp.asarray([[1.0, 0.5], [1.0, 0.5]], jnp.float64)
    s = np.asarray(re.score_ell_rows(rows, fi, fv))
    np.testing.assert_allclose(s, [2.0 + 5.0, 0.0])
