"""Loss-function unit tests: values and derivatives vs closed forms / numeric
differentiation (mirrors the reference's photon-lib function/glm/*Test suites)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses as L


def numeric_dz(loss, z, y, eps=1e-6):
    lp = loss.loss_and_dz(jnp.asarray(z + eps), jnp.asarray(y))[0]
    lm = loss.loss_and_dz(jnp.asarray(z - eps), jnp.asarray(y))[0]
    return (np.asarray(lp) - np.asarray(lm)) / (2 * eps)


@pytest.mark.parametrize("name", ["logistic", "squared", "poisson", "smoothed_hinge"])
def test_first_derivative_matches_numeric(name):
    loss = L.get_loss(name)
    zs = np.linspace(-4, 4, 41)
    for y in (0.0, 1.0) if name != "poisson" else (0.0, 1.0, 3.0, 7.0):
        z = jnp.asarray(zs)
        _, dz = loss.loss_and_dz(z, jnp.full_like(z, y))
        num = numeric_dz(loss, zs, np.full_like(zs, y))
        # smoothed hinge has kinks at m in {0, 1}; skip points within eps of them
        if name == "smoothed_hinge":
            ymod = 1.0 if y > 0.5 else -1.0
            m = ymod * zs
            mask = (np.abs(m) > 1e-3) & (np.abs(m - 1) > 1e-3)
        else:
            mask = np.ones_like(zs, bool)
        np.testing.assert_allclose(np.asarray(dz)[mask], num[mask], atol=1e-5)


@pytest.mark.parametrize("name", ["logistic", "squared", "poisson"])
def test_second_derivative_matches_numeric(name):
    loss = L.get_loss(name)
    zs = np.linspace(-3, 3, 31)
    y = np.ones_like(zs)
    _, dz_p = loss.loss_and_dz(jnp.asarray(zs + 1e-5), jnp.asarray(y))
    _, dz_m = loss.loss_and_dz(jnp.asarray(zs - 1e-5), jnp.asarray(y))
    num = (np.asarray(dz_p) - np.asarray(dz_m)) / 2e-5
    d2 = loss.d2z(jnp.asarray(zs), jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(d2), num, atol=1e-4)


def test_logistic_closed_form():
    loss = L.LOGISTIC
    z = jnp.asarray([0.0, 2.0, -2.0])
    # positive label
    l1, d1 = loss.loss_and_dz(z, jnp.ones(3))
    np.testing.assert_allclose(np.asarray(l1), np.log1p(np.exp(-np.asarray(z))), rtol=1e-12)
    # negative label
    l0, d0 = loss.loss_and_dz(z, jnp.zeros(3))
    np.testing.assert_allclose(np.asarray(l0), np.log1p(np.exp(np.asarray(z))), rtol=1e-12)
    # derivative = sigmoid(z) - y
    sig = 1 / (1 + np.exp(-np.asarray(z)))
    np.testing.assert_allclose(np.asarray(d1), sig - 1, atol=1e-12)
    np.testing.assert_allclose(np.asarray(d0), sig, atol=1e-12)


def test_logistic_numerically_stable_at_extremes():
    loss = L.LOGISTIC
    z = jnp.asarray([1000.0, -1000.0])
    l1, d1 = loss.loss_and_dz(z, jnp.ones(2))
    assert np.all(np.isfinite(np.asarray(l1)))
    assert np.all(np.isfinite(np.asarray(d1)))
    np.testing.assert_allclose(np.asarray(l1), [0.0, 1000.0], atol=1e-9)


def test_poisson_closed_form():
    loss = L.POISSON
    z = jnp.asarray([0.5, -0.5])
    y = jnp.asarray([2.0, 0.0])
    l, dz = loss.loss_and_dz(z, y)
    np.testing.assert_allclose(np.asarray(l), np.exp([0.5, -0.5]) - np.asarray(y) * np.asarray(z), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(dz), np.exp([0.5, -0.5]) - np.asarray(y), rtol=1e-12)


def test_smoothed_hinge_segments():
    loss = L.SMOOTHED_HINGE
    # y=1: m=z. z=-1 -> 0.5-(-1)=1.5 ; z=0.5 -> 0.5*0.25=0.125 ; z=2 -> 0
    l, _ = loss.loss_and_dz(jnp.asarray([-1.0, 0.5, 2.0]), jnp.ones(3))
    np.testing.assert_allclose(np.asarray(l), [1.5, 0.125, 0.0], rtol=1e-12)
    # y=0 (treated as -1): m=-z
    l0, _ = loss.loss_and_dz(jnp.asarray([-2.0, -0.5, 1.0]), jnp.zeros(3))
    np.testing.assert_allclose(np.asarray(l0), [0.0, 0.125, 1.5], rtol=1e-12)


def test_task_dispatch():
    assert L.get_loss("logistic_regression") is L.LOGISTIC
    assert L.get_loss("LINEAR_REGRESSION".lower()) is L.SQUARED
    assert L.get_loss("poisson_regression") is L.POISSON
    assert L.get_loss("smoothed_hinge_loss_linear_svm") is L.SMOOTHED_HINGE
    with pytest.raises(KeyError):
        L.get_loss("nope")
