"""Resident scoring service (photon_ml_tpu/serving): mmap store roundtrip,
cold-start fallback, batch/resident bitwise parity, microbatching, the
AF_UNIX front, and the kill-and-keep-serving refresh drill.

Parity note: per-row scores are row-independent, so padding the batch to a
ladder rung can never change a real row's bits; padding the ELL feature
width CAN regroup the reduction, so the bitwise resident-vs-batch tests pin
max row nnz = 4 = the smallest width rung (serving.engine.LADDER_WIDTH[0]).
"""

from __future__ import annotations

import os
import json
import socket
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import obs, serving
from photon_ml_tpu.estimators.game_estimator import GameTransformer
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, LogisticRegressionModel
from photon_ml_tpu.serving.engine import _ladder_rows, _ladder_width
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset

D_FIXED = 6
D_RE = 4


def make_model(fe_shift=0.0, seed=0):
    """Small two-coordinate GLMix model with deterministic coefficients."""
    rng = np.random.default_rng(seed)
    fe = FixedEffectModel(
        model=LogisticRegressionModel(
            Coefficients(jnp.asarray(rng.standard_normal(D_FIXED) + fe_shift))
        ),
        feature_shard="globalShard",
    )
    re = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=np.asarray(["uA", "uB", "uC"], dtype=object),
        coef_indices=jnp.asarray(
            [[0, 2, -1], [1, 3, -1], [0, 1, 2]], jnp.int32
        ),
        coef_values=jnp.asarray(rng.standard_normal((3, 3))),
    )
    return GameModel(models={"global": fe, "per-user": re}, task="logistic_regression")


def make_request(rng, uid):
    """Random request with nnz=4 on the global shard, nnz=2 on the user
    shard (both <= the smallest width rung, for bitwise parity)."""
    gidx = np.sort(rng.choice(D_FIXED, size=4, replace=False))
    uidx = np.sort(rng.choice(D_RE, size=2, replace=False))
    return serving.ScoreRequest(
        features={
            "globalShard": (tuple(int(i) for i in gidx),
                            tuple(rng.standard_normal(4).tolist())),
            "userShard": (tuple(int(i) for i in uidx),
                          tuple(rng.standard_normal(2).tolist())),
        },
        ids={"userId": uid},
        offset=float(rng.standard_normal()),
    )


def oracle_score(model, req):
    """Hand-assembled numpy oracle: offset + FE dot + (RE dot | 0 if unseen)."""
    total = req.offset
    fe = model.models["global"]
    w = np.asarray(fe.model.coefficients.means)
    gi, gv = req.features["globalShard"]
    total += float(np.dot(w[np.asarray(gi)], np.asarray(gv)))
    re = model.models["per-user"]
    uid = req.ids.get("userId")
    ids = list(re.entity_ids)
    if uid in ids:
        row = ids.index(uid)
        coef = {
            int(c): float(v)
            for c, v in zip(
                np.asarray(re.coef_indices)[row], np.asarray(re.coef_values)[row]
            )
            if int(c) >= 0
        }
        ui, uv = req.features["userShard"]
        total += sum(coef.get(int(c), 0.0) * float(v) for c, v in zip(ui, uv))
    return total


@pytest.fixture
def run_telemetry():
    run = obs.RunTelemetry()
    with obs.use_run(run):
        yield run


# -- store ------------------------------------------------------------------


def test_store_roundtrip_bitwise(tmp_path):
    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    store = serving.ModelStore.open(store_dir)
    assert store.task == "logistic_regression"
    by_name = {c.name: c for c in store.coords}
    fe, re = by_name["global"], by_name["per-user"]
    np.testing.assert_array_equal(
        np.asarray(fe.weights), np.asarray(model.models["global"].model.coefficients.means)
    )
    np.testing.assert_array_equal(
        np.asarray(re.coef_indices), np.asarray(model.models["per-user"].coef_indices)
    )
    np.testing.assert_array_equal(
        np.asarray(re.coef_values), np.asarray(model.models["per-user"].coef_values)
    )
    # dtype preserved exactly (f64 under the test harness)
    assert np.asarray(re.coef_values).dtype == np.asarray(
        model.models["per-user"].coef_values
    ).dtype
    np.testing.assert_array_equal(
        re.rows_for(["uA", "uC", "nobody", None]), [0, 2, -1, -1]
    )


def test_store_meta_written_last_certifies(tmp_path):
    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    os.unlink(os.path.join(store_dir, "store-meta.json"))
    with pytest.raises(Exception):
        serving.ModelStore.open(store_dir)


def test_store_version_refused(tmp_path):
    d = tmp_path / "store"
    d.mkdir()
    (d / "store-meta.json").write_text(
        json.dumps({"version": 99, "task": "x", "coordinates": []})
    )
    with pytest.raises(ValueError, match="unsupported serving store version"):
        serving.ModelStore.open(str(d))


# -- engine: cold start + oracle --------------------------------------------


def test_cold_start_fallback_and_oracle(run_telemetry):
    model = make_model()
    engine = serving.ScoreEngine.from_model(model, dtype=jnp.float64)
    rng = np.random.default_rng(7)
    # mixed batch: seen, unseen, seen, missing-id
    reqs = [
        make_request(rng, "uA"),
        make_request(rng, "stranger"),
        make_request(rng, "uC"),
        serving.ScoreRequest(
            features={"globalShard": ((0, 1), (1.0, 2.0)), "userShard": ((0,), (5.0,))}
        ),
    ]
    scores = engine.score_requests(reqs)
    expected = [oracle_score(model, r) for r in reqs]
    np.testing.assert_allclose(scores, expected, rtol=0, atol=1e-12)
    # unseen entities scored fixed-effect-only: the RE term contributed 0
    # (oracle_score already models that); the counter saw exactly the two
    # cold rows
    snap = run_telemetry.registry.snapshot()
    cold = [
        m for m in snap if m["name"] == "photon_serving_cold_start_total"
    ]
    assert len(cold) == 1
    assert cold[0]["labels"] == {"coordinate": "per-user"}
    assert cold[0]["value"] == 2


def test_warmup_does_not_count_cold_starts(run_telemetry):
    engine = serving.ScoreEngine.from_model(make_model(), dtype=jnp.float64)
    engine.warm()
    snap = run_telemetry.registry.snapshot()
    assert not [m for m in snap if m["name"] == "photon_serving_cold_start_total"]


def test_ladder_shapes():
    assert _ladder_rows(1) == 1
    assert _ladder_rows(9) == 64
    assert _ladder_rows(10**9) == serving.LADDER_ROWS[-1]
    assert _ladder_width(3) == 4
    assert _ladder_width(65) == 256
    with pytest.raises(ValueError, match="padded feature-width ladder"):
        _ladder_width(serving.LADDER_WIDTH[-1] + 1)


# -- parity ------------------------------------------------------------------


@pytest.fixture(scope="module")
def raw_dataset():
    data = generate_mixed_effect_data(
        n=60, d_fixed=D_FIXED, re_specs={"userId": (3, D_RE)}, seed=5
    )
    return mixed_data_to_raw_dataset(data)


def test_transform_and_engine_bitwise_parity(raw_dataset):
    """GameTransformer.transform and the engine's batch path produce
    bitwise-identical scores (transform delegates to the engine)."""
    model = make_model()
    engine = serving.ScoreEngine.from_model(model, dtype=jnp.float64)
    t = GameTransformer(model=model, dtype=jnp.float64)
    raw = _rename_shards(raw_dataset)
    s_t, _ = t.transform(raw)
    s_e = engine.score_dataset(raw)
    np.testing.assert_array_equal(s_t, s_e)


def _rename_shards(raw):
    """The generator emits shards named 'global'/'userId'; the test model
    uses 'globalShard'/'userShard'. Re-key the dataset's shard maps."""
    mapping = {"global": "globalShard", "userId": "userShard"}
    raw.shard_coo = {mapping.get(k, k): v for k, v in raw.shard_coo.items()}
    if getattr(raw, "shard_dims", None):
        raw.shard_dims = {mapping.get(k, k): v for k, v in raw.shard_dims.items()}
    return raw


def test_resident_vs_batch_bitwise_parity(run_telemetry):
    """The same rows scored through the resident ladder-padded path and the
    batch dataset path are bitwise-equal when the ELL width matches (max
    nnz = 4 = the smallest width rung); row padding never changes bits."""
    model = make_model()
    engine = serving.ScoreEngine.from_model(model, dtype=jnp.float64)
    rng = np.random.default_rng(11)
    reqs = [
        make_request(rng, uid)
        for uid in ["uA", "uB", "nobody", "uC", "uA", None, "uB"]
    ]
    resident = engine.score_requests(reqs)

    # hand-assemble the same rows as a batch 'dataset' at natural width 4/2
    n = len(reqs)
    offsets = np.array([r.offset for r in reqs])
    shard_ell = {}
    for shard, width in (("globalShard", 4), ("userShard", 2)):
        idx = np.zeros((n, width), dtype=np.int32)
        val = np.zeros((n, width), dtype=np.float64)
        for i, r in enumerate(reqs):
            fi, fv = r.features[shard]
            idx[i, : len(fi)] = fi
            val[i, : len(fv)] = fv
        shard_ell[shard] = (idx, val)
    # natural widths differ from the rung only for userShard (2 vs 4): pad
    # the batch side to the rung too — trailing (idx=0, val=0) pairs add
    # exact zeros, but regrouping the sum would not be bitwise-safe
    idx, val = shard_ell["userShard"]
    shard_ell["userShard"] = (
        np.pad(idx, ((0, 0), (0, 2))),
        np.pad(val, ((0, 0), (0, 2))),
    )
    re = model.models["per-user"]
    erow = re.rows_for([r.ids.get("userId") for r in reqs]).astype(np.int32)
    batch = engine.score_ell(offsets, shard_ell, {"per-user": erow})
    np.testing.assert_array_equal(resident, batch)


# -- microbatcher ------------------------------------------------------------


def test_batcher_batches_and_scores(run_telemetry):
    model = make_model()
    engine = serving.ScoreEngine.from_model(model, dtype=jnp.float64)
    engine.warm()
    b = serving.MicroBatcher(lambda: engine, max_batch=64, max_latency_ms=20.0)
    rng = np.random.default_rng(3)
    reqs = [make_request(rng, "uA") for _ in range(16)]
    futs = [b.submit(r) for r in reqs]
    got = [f.result(timeout=30.0) for f in futs]
    np.testing.assert_allclose(
        got, [oracle_score(model, r) for r in reqs], rtol=0, atol=1e-12
    )
    b.close()
    snap = run_telemetry.registry.snapshot()
    by_name = {m["name"]: m for m in snap if "count" in m or "value" in m}
    assert by_name["photon_serving_requests_total"]["value"] == 16
    assert by_name["photon_serving_request_latency_seconds"]["count"] == 16
    # at least one multi-request microbatch formed under the 20ms budget
    assert by_name["photon_serving_batch_size"]["sum"] == 16
    assert by_name["photon_serving_batch_size"]["count"] < 16


def test_batcher_error_propagates_and_counts(run_telemetry):
    def broken_engine():
        raise RuntimeError("engine exploded")

    class _Broken:
        def score_requests(self, reqs, count_cold=True):
            raise RuntimeError("engine exploded")

    b = serving.MicroBatcher(lambda: _Broken(), max_batch=4, max_latency_ms=1.0)
    fut = b.submit(serving.ScoreRequest(features={}))
    with pytest.raises(RuntimeError, match="engine exploded"):
        fut.result(timeout=30.0)
    b.close()
    snap = run_telemetry.registry.snapshot()
    errs = [m for m in snap if m["name"] == "photon_serving_request_errors_total"]
    assert errs and errs[0]["value"] == 1


def test_batcher_rejects_after_close():
    engine = serving.ScoreEngine.from_model(make_model(), dtype=jnp.float64)
    b = serving.MicroBatcher(lambda: engine)
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(serving.ScoreRequest(features={}))


# -- refresh + server: the kill-and-keep-serving drill -----------------------


def test_publish_and_open_current(tmp_path, run_telemetry):
    root = str(tmp_path / "root")
    assert serving.current_snapshot(root) is None
    serving.publish_snapshot(root, "v1", game_model=make_model())
    name, store = serving.open_current(root)
    assert name == "v1"
    assert store.task == "logistic_regression"
    with pytest.raises(FileExistsError):
        serving.publish_snapshot(root, "v1", game_model=make_model())
    with pytest.raises(ValueError, match="exactly one"):
        serving.publish_snapshot(root, "v2")


def test_refresh_survives_torn_publish(tmp_path, run_telemetry):
    root = tmp_path / "root"
    serving.publish_snapshot(str(root), "v1", game_model=make_model())
    flips = []
    w = serving.RefreshWatcher(
        str(root), lambda n, s: flips.append(n), poll_seconds=60.0, live="v1"
    )
    try:
        # CURRENT points at a snapshot that never finished publishing
        (root / "CURRENT").write_text("v2\n")
        w.poke()
        assert flips == []
        snap = run_telemetry.registry.snapshot()
        swallowed = [
            m
            for m in snap
            if m["name"] == "photon_swallowed_errors_total"
            and m["labels"].get("site") == "serving.refresh"
        ]
        assert swallowed and swallowed[0]["value"] >= 1
    finally:
        w.stop()


def test_kill_and_keep_serving_drill(tmp_path, run_telemetry):
    """Publish a new snapshot mid-stream: no request errors, every response
    comes from exactly one snapshot (v1 before the flip, v2 after — no
    stale-mixed batches), and post-flip scores bitwise-match a fresh load
    of the new model."""
    root = str(tmp_path / "root")
    m1, m2 = make_model(fe_shift=0.0), make_model(fe_shift=100.0)
    serving.publish_snapshot(root, "v1", game_model=m1)
    server = serving.ScoringServer(
        serving_root=root, max_batch=8, max_latency_ms=1.0,
        poll_seconds=3600.0, dtype=jnp.float64,
    )
    rng = np.random.default_rng(23)
    reqs = [make_request(rng, ["uA", "uB", "uC"][i % 3]) for i in range(60)]
    exp1 = np.array([oracle_score(m1, r) for r in reqs])
    exp2 = np.array([oracle_score(m2, r) for r in reqs])
    assert np.min(np.abs(exp1 - exp2)) > 1.0  # the two models are distinguishable

    try:
        futs = []
        for i, r in enumerate(reqs):
            futs.append(server.submit(r))
            if i == 20:
                serving.publish_snapshot(root, "v2", game_model=m2)
                server.poke_refresh()
            time.sleep(0.001)
        got = np.array([f.result(timeout=30.0) for f in futs])  # no errors
        from_v1 = np.isclose(got, exp1, rtol=0, atol=1e-9)
        from_v2 = np.isclose(got, exp2, rtol=0, atol=1e-9)
        # every response from exactly one model, and the stream is monotone:
        # once a response comes from v2, nothing later comes from v1
        assert np.all(from_v1 ^ from_v2)
        if from_v2.any():
            first_v2 = int(np.argmax(from_v2))
            assert np.all(from_v2[first_v2:])
        assert server.snapshot_name == "v2"
        assert from_v2.any()

        # post-flip scores bitwise-match a fresh load of the new snapshot
        fresh = serving.ScoreEngine.from_store(
            serving.ModelStore.open(serving.snapshot_path(root, "v2")),
            dtype=jnp.float64,
        )
        tail = [r for r, v2 in zip(reqs, from_v2) if v2]
        np.testing.assert_array_equal(got[from_v2], fresh.score_requests(tail))

        snap = run_telemetry.registry.snapshot()
        refreshes = [
            m for m in snap if m["name"] == "photon_serving_refresh_total"
        ]
        assert refreshes and refreshes[0]["value"] == 1
        errs = [
            m for m in snap if m["name"] == "photon_serving_request_errors_total"
        ]
        assert not errs
    finally:
        server.close()


# -- the AF_UNIX front -------------------------------------------------------


def test_socket_server_roundtrip(tmp_path, run_telemetry):
    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    server = serving.ScoringServer(
        store=serving.ModelStore.open(store_dir),
        max_latency_ms=1.0,
        dtype=jnp.float64,
    )
    sock_path = str(tmp_path / "serve.sock")
    stop = threading.Event()
    t = threading.Thread(
        target=serving.serve_socket, args=(server, sock_path, stop), daemon=True
    )
    t.start()
    try:
        deadline = time.time() + 10
        while not os.path.exists(sock_path) and time.time() < deadline:
            time.sleep(0.01)
        rng = np.random.default_rng(9)
        req = make_request(rng, "uB")
        payload = {
            "features": {k: [list(v[0]), list(v[1])] for k, v in req.features.items()},
            "ids": dict(req.ids),
            "offset": req.offset,
        }
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as c:
            c.connect(sock_path)
            f = c.makefile("rwb")
            f.write((json.dumps(payload) + "\n").encode())
            f.flush()
            resp = json.loads(f.readline())
            assert abs(resp["score"] - oracle_score(model, req)) < 1e-12
            # every response carries the request-scoped trace id
            assert resp["trace_id"]
            # malformed request -> error response, connection stays up
            f.write(b'{"features": "nonsense"}\n')
            f.flush()
            resp2 = json.loads(f.readline())
            assert "error" in resp2
            assert resp2["trace_id"]
            assert resp2["trace_id"] != resp["trace_id"]
            # client-supplied trace_id is echoed, not replaced
            payload["trace_id"] = "client-abc-123"
            f.write((json.dumps(payload) + "\n").encode())
            f.flush()
            resp3 = json.loads(f.readline())
            assert resp3["trace_id"] == "client-abc-123"
            assert abs(resp3["score"] - oracle_score(model, req)) < 1e-12
    finally:
        stop.set()
        t.join(timeout=10)
        server.close()


def test_shed_response_carries_trace_id(tmp_path, run_telemetry):
    """Sheds are responses too: the AF_UNIX front echoes the trace_id on a
    shed so the client can tie the refusal back to its request."""
    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    server = serving.ScoringServer(
        store=serving.ModelStore.open(store_dir),
        max_latency_ms=1.0,
        dtype=jnp.float64,
        default_deadline_ms=1e-6,  # expires before admission: always sheds
    )
    sock_path = str(tmp_path / "serve.sock")
    stop = threading.Event()
    t = threading.Thread(
        target=serving.serve_socket, args=(server, sock_path, stop), daemon=True
    )
    t.start()
    try:
        deadline = time.time() + 10
        while not os.path.exists(sock_path) and time.time() < deadline:
            time.sleep(0.01)
        rng = np.random.default_rng(11)
        req = make_request(rng, "uA")
        payload = {
            "features": {k: [list(v[0]), list(v[1])] for k, v in req.features.items()},
            "trace_id": "shed-trace-9",
        }
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as c:
            c.connect(sock_path)
            f = c.makefile("rwb")
            f.write((json.dumps(payload) + "\n").encode())
            f.flush()
            resp = json.loads(f.readline())
        assert resp["error_type"] == "shed"
        assert resp["trace_id"] == "shed-trace-9"
        assert resp["reason"]
    finally:
        stop.set()
        t.join(timeout=10)
        server.close()
    sheds = [
        m for m in run_telemetry.registry.snapshot()
        if m["name"] == "photon_serving_shed_total"
    ]
    assert sheds and sum(m["value"] for m in sheds) >= 1


def test_request_stage_spans_and_slow_counter(tmp_path, run_telemetry):
    """A traced request lands per-stage spans (admit/batch/score) parented
    under its serving.request root and all stamped with the trace_id; with a
    sub-millisecond slow threshold every request also trips the
    slow-request counter."""
    from photon_ml_tpu.serving.batcher import RequestTrace

    spans = []

    class _SpanTap:
        def handle(self, event):
            if isinstance(event, obs.SpanEvent):
                spans.append(event.span)

    run_telemetry.register_listener(_SpanTap())
    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    server = serving.ScoringServer(
        store=serving.ModelStore.open(store_dir),
        max_latency_ms=1.0,
        dtype=jnp.float64,
        slow_request_ms=1e-4,  # everything is "slow": the counter must fire
    )
    try:
        rng = np.random.default_rng(13)
        req = make_request(rng, "uB")
        with obs.span("serving.request", trace_id="t-42") as root:
            trace = RequestTrace(trace_id="t-42", parent=root)
            score = server.score(req, trace=trace)
        assert abs(score - oracle_score(model, req)) < 1e-12
    finally:
        server.close()

    by_name = {s.name: s for s in spans}
    for stage in ("serving.admit", "serving.batch", "serving.score"):
        assert stage in by_name, f"missing stage span {stage}"
        assert by_name[stage].parent_id == root.span_id
        assert by_name[stage].attrs["trace_id"] == "t-42"
    assert by_name["serving.request"].attrs["trace_id"] == "t-42"
    slow = [
        m for m in run_telemetry.registry.snapshot()
        if m["name"] == "photon_serving_slow_requests_total"
    ]
    assert slow and slow[0]["value"] >= 1


def test_shed_storm_one_flight_dump_zero_requests_lost(tmp_path, run_telemetry):
    """The acceptance drill: a shed storm triggers exactly ONE flight-recorder
    dump (the cooldown latch holds for the rest of the storm) and every
    submitted request still resolves — scored or cleanly shed, none lost."""
    rec = obs.FlightRecorder(
        str(tmp_path / "flight"),
        run=run_telemetry,
        shed_rate_threshold=5.0,
        poll_interval_s=0.0,
        cooldown_s=60.0,
    )
    run_telemetry.register_listener(rec)
    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    server = serving.ScoringServer(
        store=serving.ModelStore.open(store_dir),
        max_latency_ms=20.0,
        dtype=jnp.float64,
        max_pending=2,  # tiny queue: the flood sheds on queue_full
    )
    try:
        rng = np.random.default_rng(17)
        # warm-up request establishes the recorder's rate baseline
        server.score(make_request(rng, "uA"))
        assert rec.poll(force=True) is None
        time.sleep(0.05)

        futs = []
        for i in range(200):
            try:
                futs.append(server.submit(make_request(rng, "uC")))
            except serving.ShedError:
                futs.append(None)  # shed at admission: still a clean outcome
        scored = shed = 0
        for fut in futs:
            if fut is None:
                shed += 1
                continue
            try:
                fut.result(timeout=30.0)
                scored += 1
            except serving.ShedError:
                shed += 1
        assert scored + shed == 200  # zero requests lost
        assert shed >= 1  # the storm actually shed

        first = rec.poll(force=True)
        assert first is not None
        # storm keeps raging; the latch holds — still exactly one dump
        for _ in range(5):
            try:
                server.submit(make_request(rng, "uC")).result(timeout=30.0)
            except serving.ShedError:
                pass
            assert rec.poll(force=True) is None
        assert len(rec.dump_paths) == 1
        doc = json.load(open(first))
        assert doc["trigger"]["kind"] == "shed_spike"
    finally:
        server.close()


def test_cli_serve_store_dir_socket(tmp_path):
    """The cli.serve driver end to end: serve a store over the socket,
    score one request, stop, and find the Prometheus exposition (with the
    serving quantile gauges) in --metrics-out."""
    from photon_ml_tpu.cli import serve as cli_serve

    model = make_model()
    store_dir = serving.build_store_from_model(model, str(tmp_path / "store"))
    sock_path = str(tmp_path / "serve.sock")
    metrics_dir = str(tmp_path / "metrics")
    stop = threading.Event()
    t = threading.Thread(
        target=cli_serve.run,
        args=(
            [
                "--store-dir", store_dir,
                "--socket", sock_path,
                "--max-latency-ms", "1.0",
                "--metrics-out", metrics_dir,
                "--replica-id", "r3",
            ],
            stop,
        ),
        daemon=True,
    )
    t.start()
    try:
        deadline = time.time() + 30
        while not os.path.exists(sock_path) and time.time() < deadline:
            time.sleep(0.01)
        assert os.path.exists(sock_path), "cli.serve never bound its socket"
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as c:
            c.connect(sock_path)
            f = c.makefile("rwb")
            f.write(b'{"features": {"globalShard": [[0], [1.0]]}}\n')
            f.flush()
            resp = json.loads(f.readline())
        w = np.asarray(model.models["global"].model.coefficients.means)
        assert abs(resp["score"] - float(w[0])) < 1e-6
        assert resp["trace_id"]
    finally:
        stop.set()
        t.join(timeout=30)
    assert not t.is_alive()
    prom = os.path.join(metrics_dir, "metrics.prom")
    assert os.path.exists(prom)
    text = open(prom).read()
    assert "photon_serving_request_latency_seconds_p99" in text
    assert "photon_serving_requests_total" in text
    # --replica-id stamps the obs identity into the build-info gauge
    assert "photon_build_info{" in text
    assert 'replica="r3"' in text
    # the serve driver arms a flight recorder beside the metric sinks
    assert os.path.isdir(os.path.join(metrics_dir, "flight"))


# -- prometheus quantiles ----------------------------------------------------


def test_serving_quantiles_in_prometheus(run_telemetry):
    reg = run_telemetry.registry
    h = reg.histogram(
        "photon_serving_request_latency_seconds",
        "t",
        buckets=serving.SERVING_LATENCY_BUCKETS,
    )
    for v in [0.001] * 50 + [0.004] * 45 + [0.2] * 5:
        h.observe(v)
    text = obs.render_prometheus(reg.snapshot())
    assert "photon_serving_request_latency_seconds_p50" in text
    assert "photon_serving_request_latency_seconds_p95" in text
    assert "photon_serving_request_latency_seconds_p99" in text
    # quantile gauges render for every histogram family, serving or not
    reg.histogram("photon_other", "t").observe(1.0)
    text = obs.render_prometheus(reg.snapshot())
    assert "# TYPE photon_other_p50 gauge" in text


def test_histogram_quantile_interpolation():
    # 100 obs: 50 in (0, 1], 45 in (1, 5], 5 in (5, +Inf)
    buckets = [(1.0, 50), (5.0, 95)]
    assert obs.histogram_quantile(buckets, 100, 0.5) == 1.0
    # p90 -> rank 90 inside (1, 5]: 1 + 4 * (90-50)/45
    assert abs(obs.histogram_quantile(buckets, 100, 0.9) - (1 + 4 * 40 / 45)) < 1e-12
    # target beyond the last finite bucket clamps to its upper bound
    assert obs.histogram_quantile(buckets, 100, 0.99) == 5.0
    assert obs.histogram_quantile(buckets, 0, 0.5) == 0.0
    assert obs.histogram_quantile([], 10, 0.5) == 0.0


def test_retrain_publish_three_daily_flips_drill(tmp_path, run_telemetry):
    """Continuous-training drill: three consecutive daily publishes through
    the chain's real publish path (incremental._ensure_published) flip a
    live server mid-stream. Zero lost requests, every response from exactly
    one published model, and the flip sequence is monotone day over day."""
    from photon_ml_tpu.game import incremental

    root = str(tmp_path / "root")
    models = [make_model(fe_shift=100.0 * k, seed=5) for k in range(4)]
    serving.publish_snapshot(root, "retrain-20260101", game_model=models[0])
    server = serving.ScoringServer(
        serving_root=root, max_batch=8, max_latency_ms=1.0,
        poll_seconds=3600.0, dtype=jnp.float64,
    )
    rng = np.random.default_rng(41)
    reqs = [make_request(rng, ["uA", "uB", "uC"][i % 3]) for i in range(80)]
    exp = np.stack([[oracle_score(m, r) for r in reqs] for m in models])
    # any two of the four dailies are distinguishable on every request
    for a in range(4):
        for b in range(a + 1, 4):
            assert np.min(np.abs(exp[a] - exp[b])) > 1.0

    def _publish(day_index):
        day = f"2026010{day_index + 1}"
        rec = incremental.DayRecord(
            day=day, index=day_index, accepted=True, reason="accepted",
            rows=0, touched_entities={}, snapshot=f"retrain-{day}",
        )
        assert incremental._ensure_published(root, rec, models[day_index])

    try:
        futs = []
        for i, r in enumerate(reqs):
            futs.append(server.submit(r))
            if i in (20, 40, 60):  # three consecutive daily flips mid-stream
                _publish(i // 20)
                server.poke_refresh()
            time.sleep(0.001)
        got = np.array([f.result(timeout=30.0) for f in futs])  # zero lost
        source = np.full(len(reqs), -1)
        for k in range(4):
            hit = np.isclose(got, exp[k], rtol=0, atol=1e-9)
            assert np.all(source[hit] == -1)  # exactly one model per response
            source[hit] = k
        assert np.all(source >= 0)
        # day-over-day monotone: the served model index never goes backwards
        assert np.all(np.diff(source) >= 0)
        assert source[-1] == 3 and server.snapshot_name == "retrain-20260104"

        snap = run_telemetry.registry.snapshot()
        refreshes = [
            m for m in snap if m["name"] == "photon_serving_refresh_total"
        ]
        assert refreshes and refreshes[0]["value"] == 3
        published = [
            m for m in snap if m["name"] == "photon_retrain_published_total"
        ]
        assert published and published[0]["value"] == 3
        errs = [
            m for m in snap if m["name"] == "photon_serving_request_errors_total"
        ]
        assert not errs
    finally:
        server.close()
