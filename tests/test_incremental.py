"""Incremental training tests: prior-centered L2 regularization
("Regularize by Previous Model During Warm-Start Training", README.md:102-103):
multiple warm-start rounds on data slices should approach cold-start training
on the full data."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.estimators import CoordinateConfig, GameEstimator
from photon_ml_tpu.game.problem import GLMOptimizationConfig, GLMProblem
from photon_ml_tpu.models import Coefficients
from photon_ml_tpu.ops import GLMObjective, LOGISTIC, batch_from_dense
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.testing import generate_glm_data, generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset


def test_prior_objective_math(rng):
    x, y, _ = generate_glm_data(n=50, d=5, seed=1)
    batch = batch_from_dense(x, y, dtype=jnp.float64)
    mean = jnp.asarray(rng.normal(size=5))
    prec = jnp.asarray(rng.uniform(0.5, 2.0, size=5))
    lam = 2.0
    obj = GLMObjective(
        loss=LOGISTIC, batch=batch, l2=lam, prior_mean=mean, prior_precision=prec
    )
    obj_plain = GLMObjective(loss=LOGISTIC, batch=batch, l2=0.0)
    w = jnp.asarray(rng.normal(size=5))
    v_prior, g_prior = obj.value_and_grad(w)
    v_plain, g_plain = obj_plain.value_and_grad(w)
    delta = np.asarray(w) - np.asarray(mean)
    np.testing.assert_allclose(
        float(v_prior),
        float(v_plain) + 0.5 * lam * np.sum(np.asarray(prec) * delta**2),
        rtol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(g_prior),
        np.asarray(g_plain) + lam * np.asarray(prec) * delta,
        rtol=1e-10,
    )
    # Hv/Hdiag consistent with autodiff
    hv_auto = jax.jvp(lambda c: jax.grad(obj.value)(c), (w,), (w,))[1]
    np.testing.assert_allclose(np.asarray(obj.hessian_vector(w, w)), np.asarray(hv_auto), rtol=1e-8)
    h_auto = np.asarray(jax.hessian(obj.value)(w))
    np.testing.assert_allclose(np.asarray(obj.hessian_diagonal(w)), np.diag(h_auto), rtol=1e-8)


def test_incremental_rounds_approach_full_training(rng):
    """Train on slice 1, then slice 2 with the round-1 posterior as prior;
    should land closer to full-data training than training on slice 2 alone."""
    x, y, w_true = generate_glm_data(n=2000, d=8, seed=3)
    lam = 1.0
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-10, max_iterations=300),
        regularization=RegularizationContext("L2"),
        reg_weight=lam,
        variance_type="SIMPLE",
    )

    def fit(xs, ys, prior=None):
        batch = batch_from_dense(xs, ys, dtype=jnp.float64)
        problem = GLMProblem(task="logistic_regression", config=cfg, prior=prior)
        model, _ = problem.run(batch)
        return model

    full = fit(x, y)
    half1 = fit(x[:1000], y[:1000])
    # round 2: second half with round-1 posterior as prior
    incremental = fit(
        x[1000:], y[1000:],
        prior=Coefficients(
            means=half1.coefficients.means, variances=half1.coefficients.variances
        ),
    )
    alone = fit(x[1000:], y[1000:])

    w_full = np.asarray(full.coefficients.means)
    err_inc = np.linalg.norm(np.asarray(incremental.coefficients.means) - w_full)
    err_alone = np.linalg.norm(np.asarray(alone.coefficients.means) - w_full)
    assert err_inc < err_alone


def test_game_estimator_incremental(rng):
    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(n=1000, d_fixed=6, re_specs={"userId": (20, 4)}, seed=41)
    )
    opt = OptimizerConfig(tolerance=1e-8, max_iterations=100)
    base = [
        CoordinateConfig(
            name="global", feature_shard="global",
            config=GLMOptimizationConfig(
                optimizer=opt, regularization=RegularizationContext("L2"),
                reg_weight=1.0, variance_type="SIMPLE",
            ),
        ),
        CoordinateConfig(
            name="per-user", feature_shard="userShard", random_effect_type="userId",
            config=GLMOptimizationConfig(
                optimizer=opt, regularization=RegularizationContext("L2"), reg_weight=1.0
            ),
        ),
    ]
    est = GameEstimator(task="logistic_regression", coordinate_configs=base, dtype=jnp.float64)
    first = est.fit(raw)[0]

    # round 2 with a STRONG prior weight: the prior pins coefficients to the
    # round-1 model, whereas plain L2 at the same weight would crush them to 0
    strong = [
        dataclasses.replace(
            c,
            regularize_by_prior=True,
            config=dataclasses.replace(c.config, reg_weight=1000.0),
            reg_weights=(1000.0,),
        )
        for c in base
    ]
    est2 = GameEstimator(
        task="logistic_regression", coordinate_configs=strong, dtype=jnp.float64
    )
    second = est2.fit(raw, initial_model=first.model)[0]

    plain = [
        dataclasses.replace(
            c,
            config=dataclasses.replace(c.config, reg_weight=1000.0),
            reg_weights=(1000.0,),
        )
        for c in base
    ]
    est3 = GameEstimator(
        task="logistic_regression", coordinate_configs=plain, dtype=jnp.float64
    )
    third = est3.fit(raw)[0]

    w1 = np.asarray(first.model["global"].model.coefficients.means)
    w2 = np.asarray(second.model["global"].model.coefficients.means)
    w3 = np.asarray(third.model["global"].model.coefficients.means)
    assert np.linalg.norm(w2 - w1) < 0.1  # pinned to the prior
    # Plain L2 at weight 1000 shrinks toward (not to) zero: with the
    # sum-convention objective over n=1000 rows the data term and the penalty
    # are comparable, so the exact minimizer keeps ~1/6 of the norm. Check
    # against the closed-form minimizer (scipy, offset-free global shard) —
    # a strictly stronger check than a norm bound.
    from scipy.optimize import minimize

    from photon_ml_tpu.game import build_fixed_effect_dataset

    b = build_fixed_effect_dataset(raw, "global", "global", layout="dense").batch
    x_np = np.asarray(b.features.to_dense())
    y_np = np.asarray(b.labels)
    wt_np = np.asarray(b.weights)

    def plain_l2_objective(c):
        z = x_np @ c
        loss = np.logaddexp(0.0, z) - y_np * z
        return np.sum(wt_np * loss) + 0.5 * 1000.0 * np.dot(c, c)

    w_star = minimize(
        plain_l2_objective,
        np.zeros(x_np.shape[1]),
        method="L-BFGS-B",
        options={"maxiter": 2000, "ftol": 1e-15, "gtol": 1e-12},
    ).x
    np.testing.assert_allclose(w3, w_star, atol=1e-5)
    assert np.linalg.norm(w3) < 0.25 * np.linalg.norm(w1)  # still strong shrinkage
    r1 = np.asarray(first.model["per-user"].coef_values)
    r2 = np.asarray(second.model["per-user"].coef_values)
    assert np.abs(r2 - r1).max() < 0.1
