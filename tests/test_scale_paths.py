"""Round-3 scale-path regressions: COO row slicing, vectorized model
projection at non-trivial sizes, and shard-aligned size buckets."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.data import build_random_effect_dataset
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.ops.features import FeatureMatrix, sorted_coo_matrix
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _random_coo(rng, n=40, d=25, nnz=160):
    rows = rng.integers(0, n, size=nnz)
    cols = rng.integers(0, d, size=nnz)
    vals = rng.normal(size=nnz)
    # collapse duplicate (row, col) pairs — scatter-add would double-count
    keys = rows * d + cols
    _, first = np.unique(keys, return_index=True)
    return rows[first], cols[first], vals[first], n, d


def test_coo_slice_rows_matches_dense(rng):
    rows, cols, vals, n, d = _random_coo(rng)
    fm = sorted_coo_matrix(rows, cols, vals, n_rows=n, dim=d, dtype=jnp.float32)
    dense = np.asarray(fm.to_dense())
    w = rng.normal(size=d).astype(np.float32)
    c = rng.normal(size=12).astype(np.float32)
    for start in (0, 5, n - 12):
        sl = fm.slice_rows(start, 12)
        assert sl.layout == "coo" and sl.n_rows == 12
        np.testing.assert_allclose(
            np.asarray(sl.to_dense()), dense[start : start + 12], rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(sl.matvec(jnp.asarray(w))),
            dense[start : start + 12] @ w,
            rtol=1e-4,
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(sl.rmatvec(jnp.asarray(c))),
            dense[start : start + 12].T @ c,
            rtol=1e-4,
            atol=1e-5,
        )


def test_coo_slice_rows_under_jit(rng):
    rows, cols, vals, n, d = _random_coo(rng)
    fm = sorted_coo_matrix(rows, cols, vals, n_rows=n, dim=d, dtype=jnp.float32)
    dense = np.asarray(fm.to_dense())
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))

    @jax.jit
    def windowed_margins(fm, start):
        return fm.slice_rows(start, 8).matvec(w)

    for start in (0, 3, 17):
        np.testing.assert_allclose(
            np.asarray(windowed_margins(fm, start)),
            dense[start : start + 8] @ np.asarray(w),
            rtol=1e-4,
            atol=1e-5,
        )


def test_project_model_values_general_path_large(rng):
    """Vectorized sorted-key projection == naive per-entity loop, with a
    permuted-entity model whose support layout differs from the dataset's."""
    from photon_ml_tpu.game.coordinate import _project_model_values

    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=6000, d_fixed=4, re_specs={"userId": (400, 12)}, seed=3, entity_skew=1.3
        )
    )
    ds = build_random_effect_dataset(raw, "re", "userShard", "userId")
    E, S = ds.blocks.proj_cols.shape
    d_shard = raw.shard_dims["userShard"]

    # model over a permutation of the dataset's entities (plus some unseen),
    # each with its own random support
    perm = rng.permutation(E)
    model_ids = np.concatenate(
        [np.asarray(ds.entity_ids, dtype=object)[perm], np.asarray(["ghost1", "ghost2"], dtype=object)]
    )
    Em, Sm = len(model_ids), 9
    idx = np.full((Em, Sm), -1, dtype=np.int32)
    val = np.zeros((Em, Sm))
    for e in range(Em):
        k = int(rng.integers(1, Sm + 1))
        idx[e, :k] = np.sort(rng.choice(d_shard, size=k, replace=False))
        val[e, :k] = rng.normal(size=k)
    model = RandomEffectModel(
        random_effect_type="userId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=model_ids,
        coef_indices=jnp.asarray(idx),
        coef_values=jnp.asarray(val, jnp.float32),
    )

    got = np.asarray(
        _project_model_values(ds, model, model.coef_values, jnp.float32)
    )

    # naive reference
    rows = model.rows_for(ds.entity_ids)
    pc = np.asarray(ds.blocks.proj_cols)
    vals32 = np.asarray(model.coef_values)
    expected = np.zeros((E, S), dtype=np.float32)
    for e in range(E):
        r = rows[e]
        if r < 0:
            continue
        lookup = {int(c): vals32[r, j] for j, c in enumerate(idx[r]) if c >= 0}
        for j, c in enumerate(pc[e]):
            if c >= 0 and int(c) in lookup:
                expected[e, j] = lookup[int(c)]
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_size_buckets_align():
    from photon_ml_tpu.game.coordinate import _size_buckets

    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=3000, d_fixed=4, re_specs={"userId": (64, 8)}, seed=5, entity_skew=1.8
        )
    )
    ds = build_random_effect_dataset(
        raw, "re", "userShard", "userId", active_cap=64, pad_entities_to_multiple=8
    )
    plain = _size_buckets(ds)
    assert plain is not None and len(plain) > 1
    E = ds.blocks.features.shape[0]
    chunk = E // 8  # per-device entity chunk on an 8-way mesh
    aligned = _size_buckets(ds, align=chunk)
    assert aligned is not None
    for start, end, kb, sb in aligned:
        assert start % chunk == 0 and (end % chunk == 0 or end == E)
    # segments must tile [0, E) and keep K_b >= every segment entity's count
    assert aligned[0][0] == 0 and aligned[-1][1] == E
    counts = np.asarray(ds.entity_counts)
    for start, end, kb, sb in aligned:
        assert counts[start:end].max(initial=0) <= kb


def test_estimator_tiled_fixed_effect_matches_dense():
    """The huge-d product path: GameEstimator with layout='tiled' on a
    (data=4 x model=2) mesh + two random effects == the single-device dense
    run, through the public fit() surface."""
    from photon_ml_tpu.estimators.game_estimator import CoordinateConfig, GameEstimator
    from photon_ml_tpu.game import GLMOptimizationConfig
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel import make_mesh

    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=800,
            d_fixed=10,
            re_specs={"userId": (24, 5), "itemId": (12, 4)},
            seed=21,
        )
    )

    def coords(layout):
        cfg = GLMOptimizationConfig(
            optimizer=OptimizerConfig(tolerance=1e-8, max_iterations=40),
            regularization=RegularizationContext("L2"),
            reg_weight=1.0,
        )
        return [
            CoordinateConfig(
                name="global", feature_shard="global", config=cfg, layout=layout
            ),
            CoordinateConfig(
                name="per-user",
                feature_shard="userShard",
                config=cfg,
                random_effect_type="userId",
            ),
            CoordinateConfig(
                name="per-item",
                feature_shard="itemShard",
                config=cfg,
                random_effect_type="itemId",
            ),
        ]

    ref = GameEstimator(
        task="logistic_regression", coordinate_configs=coords("dense"), n_cd_iterations=2
    ).fit(raw)[-1]

    mesh = make_mesh(n_data=4, n_model=2)
    tiled = GameEstimator(
        task="logistic_regression",
        coordinate_configs=coords("tiled"),
        n_cd_iterations=2,
        mesh=mesh,
    ).fit(raw)[-1]

    w_ref = np.asarray(ref.model["global"].model.coefficients.means)
    w_tiled = np.asarray(tiled.model["global"].model.coefficients.means)
    assert w_tiled.shape == w_ref.shape  # padding trimmed back to true d
    np.testing.assert_allclose(w_tiled, w_ref, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(
        np.asarray(tiled.model["per-user"].coef_values),
        np.asarray(ref.model["per-user"].coef_values),
        rtol=2e-3,
        atol=2e-3,
    )


def test_cli_trains_coo_layout(tmp_path):
    """A CLI run trains a sorted-COO fixed effect end-to-end (VERDICT r2
    item 1: the huge-d layouts must be reachable from the driver)."""
    from photon_ml_tpu.cli.train import run as train_run
    from photon_ml_tpu.io import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing.generators import generate_game_records

    data = generate_mixed_effect_data(
        n=400, d_fixed=8, re_specs={"userId": (10, 4)}, seed=2
    )
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    train_path = str(tmp_path / "train.avro")
    write_avro_file(train_path, schema, generate_game_records(data))

    out = str(tmp_path / "out")
    summary = train_run(
        [
            "--input-data", train_path,
            "--validation-data", train_path,
            "--task", "logistic_regression",
            "--feature-shard", "name=global,bags=features",
            "--feature-shard", "name=userShard,bags=userFeatures",
            "--coordinate",
            "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1,layout=coo",
            "--coordinate",
            "name=per-user,shard=userShard,re.type=userId,reg.type=L2,reg.weights=1",
            "--evaluators", "AUC",
            "--output-dir", out,
        ]
    )
    assert summary["best"]["metrics"]["AUC"] > 0.6


def test_aligned_bucket_solve_matches_unaligned():
    """Alignment only merges buckets — the solve must be unchanged."""
    import dataclasses as dc

    from photon_ml_tpu.game import GLMOptimizationConfig, RandomEffectCoordinate
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel import data_parallel_mesh, shard_entity_blocks

    raw = mixed_data_to_raw_dataset(
        generate_mixed_effect_data(
            n=2000, d_fixed=4, re_specs={"userId": (48, 8)}, seed=9, entity_skew=1.6
        )
    )
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-9, max_iterations=50),
        regularization=RegularizationContext("L2"),
        reg_weight=0.5,
    )
    ds = build_random_effect_dataset(
        raw, "re", "userShard", "userId", active_cap=64, pad_entities_to_multiple=8
    )
    m_plain, _ = RandomEffectCoordinate(
        dataset=ds, task="logistic_regression", config=cfg
    ).train(None)

    mesh = data_parallel_mesh(8)
    ds_sharded = dc.replace(ds, blocks=shard_entity_blocks(ds.blocks, mesh))
    m_sharded, _ = RandomEffectCoordinate(
        dataset=ds_sharded, task="logistic_regression", config=cfg
    ).train(None)
    # equality up to solver/f32 noise: different bucket shapes tile the f32
    # reductions differently, and 50 L-BFGS iterations amplify that to ~1e-4
    np.testing.assert_allclose(
        np.asarray(m_plain.coef_values),
        np.asarray(m_sharded.coef_values),
        rtol=2e-3,
        atol=2e-3,
    )
