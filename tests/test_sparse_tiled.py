"""Huge-d sparse path: sorted-COO layout and (data x model)-tiled sharding.

VERDICT.md round-1 item 1: an 8-device virtual-mesh test asserting that the
model-axis-sharded fixed-effect solve is exactly the replicated solve, plus
kernel-level parity of every layout against dense.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import GLMObjective, LOGISTIC, batch_from_coo, batch_from_dense
from photon_ml_tpu.ops.features import sorted_coo_matrix
from photon_ml_tpu.optimize import OptimizerConfig, optimize
from photon_ml_tpu.parallel import make_mesh
from photon_ml_tpu.parallel.sparse import (
    TiledSparseMatrix,
    replicated_coefficients,
    tile_sparse_matrix,
    tiled_sparse_batch,
)


def _random_coo(rng, n, d, k):
    rows = np.repeat(np.arange(n), k)
    cols = rng.integers(0, d, size=n * k)
    vals = rng.normal(size=n * k)
    # merge duplicate (row, col) pairs like a real dataset build would
    keys = rows.astype(np.int64) * d + cols
    uniq, inv = np.unique(keys, return_inverse=True)
    merged = np.zeros(len(uniq))
    np.add.at(merged, inv, vals)
    return (uniq // d).astype(np.int64), (uniq % d).astype(np.int64), merged


def _dense_of(rows, cols, vals, n, d):
    x = np.zeros((n, d))
    np.add.at(x, (rows, cols), vals)
    return x


def test_sorted_coo_matches_dense(rng):
    n, d, k = 64, 300, 5
    rows, cols, vals = _random_coo(rng, n, d, k)
    x = _dense_of(rows, cols, vals, n, d)
    fm = sorted_coo_matrix(rows, cols, vals, n_rows=n, dim=d, dtype=jnp.float64)
    w = rng.normal(size=d)
    c = rng.normal(size=n)
    np.testing.assert_allclose(np.asarray(fm.matvec(jnp.asarray(w))), x @ w, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(fm.rmatvec(jnp.asarray(c))), x.T @ c, rtol=1e-10)
    np.testing.assert_allclose(
        np.asarray(fm.sq_rmatvec(jnp.asarray(c))), (x * x).T @ c, rtol=1e-10
    )
    np.testing.assert_allclose(np.asarray(fm.to_dense()), x, rtol=1e-12)


@pytest.mark.parametrize("shape", [(1, 1), (8, 1), (1, 8), (4, 2), (2, 4)])
def test_tiled_matches_dense_all_mesh_shapes(rng, shape):
    n, d, k = 96, 200, 4
    rows, cols, vals = _random_coo(rng, n, d, k)
    x = _dense_of(rows, cols, vals, n, d)
    mesh = make_mesh(n_data=shape[0], n_model=shape[1])
    fm = tile_sparse_matrix(rows, cols, vals, n, d, mesh, dtype=jnp.float64)
    w = np.zeros(fm.dim)
    w[:d] = rng.normal(size=d)
    c = np.zeros(fm.n_rows)
    c[:n] = rng.normal(size=n)
    z = np.asarray(fm.matvec(replicated_coefficients(w, mesh, jnp.float64)))
    np.testing.assert_allclose(z[:n], x @ w[:d], rtol=1e-10)
    assert np.all(z[n:] == 0)
    g = np.asarray(fm.rmatvec(jnp.asarray(c)))
    np.testing.assert_allclose(g[:d], x.T @ c[:n], rtol=1e-10)
    assert np.all(g[d:] == 0)
    g2 = np.asarray(fm.sq_rmatvec(jnp.asarray(c)))
    np.testing.assert_allclose(g2[:d], (x * x).T @ c[:n], rtol=1e-10)


def test_sharded_solve_equals_replicated_solve(rng):
    """The headline invariant: L-BFGS on the (data=2 x model=4)-tiled sparse
    objective lands on the same coefficients as the plain single-device dense
    solve."""
    n, d, k = 400, 257, 6  # d deliberately not a multiple of the model axis
    rows, cols, vals = _random_coo(rng, n, d, k)
    x = _dense_of(rows, cols, vals, n, d)
    logits = x @ (rng.normal(size=d) * 0.5)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float64)

    cfg = OptimizerConfig(tolerance=1e-10, max_iterations=200)
    lam = 0.5

    # replicated dense reference
    dense_batch = batch_from_dense(x, y, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=dense_batch, l2=lam)
    res_ref = optimize(obj.value_and_grad, jnp.zeros(d, jnp.float64), cfg)

    # tiled sharded solve
    mesh = make_mesh(n_data=2, n_model=4)
    tb = tiled_sparse_batch(rows, cols, vals, y, d, mesh, dtype=jnp.float64)
    obj_t = GLMObjective(loss=LOGISTIC, batch=tb, l2=lam)
    w0 = replicated_coefficients(np.zeros(tb.features.dim), mesh, jnp.float64)
    res_t = optimize(obj_t.value_and_grad, w0, cfg)

    w_sharded = np.asarray(res_t.coefficients)
    np.testing.assert_allclose(w_sharded[:d], np.asarray(res_ref.coefficients), atol=1e-8)
    assert np.all(w_sharded[d:] == 0)

    # and the COO single-device layout agrees too
    coo_batch = batch_from_coo(rows, cols, vals, y, d, dtype=jnp.float64, layout="coo")
    obj_c = GLMObjective(loss=LOGISTIC, batch=coo_batch, l2=lam)
    res_c = optimize(obj_c.value_and_grad, jnp.zeros(d, jnp.float64), cfg)
    np.testing.assert_allclose(
        np.asarray(res_c.coefficients), np.asarray(res_ref.coefficients), atol=1e-8
    )


def test_tiled_objective_value_grad_parity(rng):
    n, d, k = 128, 97, 3
    rows, cols, vals = _random_coo(rng, n, d, k)
    x = _dense_of(rows, cols, vals, n, d)
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    mesh = make_mesh(n_data=4, n_model=2)
    tb = tiled_sparse_batch(rows, cols, vals, y, d, mesh, dtype=jnp.float64)
    obj_t = GLMObjective(loss=LOGISTIC, batch=tb, l2=0.25)
    obj_d = GLMObjective(
        loss=LOGISTIC, batch=batch_from_dense(x, y, dtype=jnp.float64), l2=0.25
    )
    w = rng.normal(size=d)
    w_pad = np.zeros(tb.features.dim)
    w_pad[:d] = w
    v_t, g_t = obj_t.value_and_grad(replicated_coefficients(w_pad, mesh, jnp.float64))
    v_d, g_d = obj_d.value_and_grad(jnp.asarray(w))
    np.testing.assert_allclose(float(v_t), float(v_d), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g_t)[:d], np.asarray(g_d), rtol=1e-9)
    # Hessian diagonal (SIMPLE variance path) also agrees
    np.testing.assert_allclose(
        np.asarray(obj_t.hessian_diagonal(replicated_coefficients(w_pad, mesh, jnp.float64)))[:d],
        np.asarray(obj_d.hessian_diagonal(jnp.asarray(w))),
        rtol=1e-9,
    )


@pytest.mark.parametrize("shape", [(4, 2), (2, 4), (8, 1), (1, 8)])
def test_tiled_full_variance_matches_dense(rng, shape):
    """variance=FULL on the tiled layout (round-3 missing item 5): the chunked
    sharded X^T diag(c) X equals the dense full Hessian, and the resulting
    diag-of-inverse variances match the dense FULL path on the true dims."""
    from photon_ml_tpu.ops.glm import compute_variances

    n, d, k = 128, 101, 3  # d not a multiple of the model axis: padded dims
    rows, cols, vals = _random_coo(rng, n, d, k)
    x = _dense_of(rows, cols, vals, n, d)
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    mesh = make_mesh(n_data=shape[0], n_model=shape[1])
    tb = tiled_sparse_batch(rows, cols, vals, y, d, mesh, dtype=jnp.float64)
    obj_t = GLMObjective(loss=LOGISTIC, batch=tb, l2=0.25)
    obj_d = GLMObjective(
        loss=LOGISTIC, batch=batch_from_dense(x, y, dtype=jnp.float64), l2=0.25
    )
    w = rng.normal(size=d) * 0.3
    w_pad = np.zeros(tb.features.dim)
    w_pad[:d] = w
    w_t = replicated_coefficients(w_pad, mesh, jnp.float64)

    h_t = np.asarray(obj_t.hessian_matrix(w_t))
    h_d = np.asarray(obj_d.hessian_matrix(jnp.asarray(w)))
    np.testing.assert_allclose(h_t[:d, :d], h_d, rtol=1e-9, atol=1e-12)
    # padded dims: unit diagonal, zero off-diagonal (invertible, inert)
    pad = tb.features.dim - d
    if pad:
        np.testing.assert_allclose(h_t[d:, d:], np.eye(pad) * (1.0 + 0.25))
        assert np.all(h_t[:d, d:] == 0) and np.all(h_t[d:, :d] == 0)

    v_t = np.asarray(compute_variances(obj_t, w_t, "FULL"))
    v_d = np.asarray(compute_variances(obj_d, jnp.asarray(w), "FULL"))
    np.testing.assert_allclose(v_t[:d], v_d, rtol=1e-8)

    # a small row_chunk exercises the multi-chunk scan path
    h_chunked = np.asarray(tb.features.xtcx(obj_t._d2z_weights(w_t), row_chunk=16))
    np.testing.assert_allclose(h_chunked[:d, :d] , h_d - 0.25 * np.eye(d), rtol=1e-9, atol=1e-12)


def test_tiled_normalization_matches_dense(rng):
    """Normalization on the tiled layout (VERDICT r4 missing item 2): the
    shift/factor algebra is layout-agnostic, so a tiled solve with
    STANDARDIZATION stats padded to the mesh dim must land on the dense
    solve's model (original space), including FULL variances with the
    rank-1-corrected tiled Hessian."""
    from photon_ml_tpu.game.problem import GLMOptimizationConfig, GLMProblem
    from photon_ml_tpu.ops.normalization import build_normalization
    from photon_ml_tpu.ops.regularization import RegularizationContext

    n, d, k = 300, 101, 4  # d deliberately not a multiple of the model axis
    rows, cols, vals = _random_coo(rng, n, d - 1, k)
    # explicit intercept column at d-1
    rows = np.concatenate([rows, np.arange(n)])
    cols = np.concatenate([cols, np.full(n, d - 1)])
    vals = np.concatenate([vals, np.ones(n)])
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    x = _dense_of(rows, cols, vals, n, d)
    # non-trivial scales so normalization actually changes the trajectory
    x[:, : d - 1] *= 1.0 + 9.0 * rng.uniform(size=d - 1)
    vals = x[rows, cols]
    logits = x @ (rng.normal(size=d) * 0.3)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float64)

    norm = build_normalization(
        "STANDARDIZATION", x.mean(0), x.var(0), np.abs(x).max(0),
        intercept_index=d - 1, dtype=jnp.float64,
    )
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-10, max_iterations=200),
        regularization=RegularizationContext("L2"),
        reg_weight=0.5,
        variance_type="FULL",
    )
    problem = GLMProblem(
        task="logistic_regression", config=cfg, normalization=norm
    )

    dense_batch = batch_from_dense(x, y, dtype=jnp.float64)
    m_dense, r_dense = problem.run(dense_batch)

    mesh = make_mesh(n_data=4, n_model=2)
    tb = tiled_sparse_batch(rows, cols, vals, y, d, mesh, dtype=jnp.float64)
    m_tiled, r_tiled = problem.run(tb)

    w_t = np.asarray(m_tiled.coefficients.means)
    np.testing.assert_allclose(
        w_t[:d], np.asarray(m_dense.coefficients.means), atol=1e-7
    )
    assert np.all(w_t[d:] == 0)
    v_t = np.asarray(m_tiled.coefficients.variances)
    np.testing.assert_allclose(
        v_t[:d], np.asarray(m_dense.coefficients.variances), rtol=1e-6
    )


def test_full_variance_dim_ceiling_consistent(rng):
    """The FULL-variance dim ceiling raises ONE exception type (ValueError)
    from every entry point, and raises EARLY — before any solve (ADVICE r4:
    divergent ValueError/NotImplementedError). d <= 16384 is in range now
    (round 5 raised the 8192 cap with the chunked Cholesky solve path; 16384
    is the measured 16 GB-chip ceiling — see ops/glm.py)."""
    from photon_ml_tpu.game.problem import GLMOptimizationConfig, GLMProblem
    from photon_ml_tpu.ops.glm import (
        MAX_FULL_VARIANCE_DIM,
        check_full_variance_dim,
    )
    from photon_ml_tpu.ops.regularization import RegularizationContext

    assert MAX_FULL_VARIANCE_DIM >= 16384
    check_full_variance_dim(MAX_FULL_VARIANCE_DIM)  # in range: no raise
    with pytest.raises(ValueError, match="variance=FULL"):
        check_full_variance_dim(MAX_FULL_VARIANCE_DIM + 1)

    # pre-solve entry point raises the same error for an over-cap tiled batch
    n, d_over = 64, MAX_FULL_VARIANCE_DIM + 8
    rows, cols, vals = _random_coo(rng, n, 50, 3)
    mesh = make_mesh(n_data=4, n_model=2)
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    tb = tiled_sparse_batch(rows, cols, vals, y, d_over, mesh, dtype=jnp.float64)
    problem = GLMProblem(
        task="logistic_regression",
        config=GLMOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=1),
            regularization=RegularizationContext("L2"),
            reg_weight=1.0,
            variance_type="FULL",
        ),
    )
    with pytest.raises(ValueError, match="variance=FULL"):
        problem.run(tb)
    # direct hessian_matrix call: same exception type, raised pre-densify
    obj = GLMObjective(loss=LOGISTIC, batch=tb, l2=1.0)
    with pytest.raises(ValueError, match="variance=FULL"):
        obj.hessian_matrix(jnp.zeros(tb.features.dim))
