"""Distributed tests on the virtual 8-device CPU mesh — the cluster-free
equivalent of the reference's local-Spark integration tests (SURVEY.md §4):
the same sharded code paths run, with XLA inserting the collectives.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game import (
    CoordinateDescent,
    FixedEffectCoordinate,
    GLMOptimizationConfig,
    RandomEffectCoordinate,
    build_fixed_effect_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.ops import GLMObjective, LOGISTIC, batch_from_dense
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig, solve_lbfgs
from photon_ml_tpu.optimize.common import abs_tolerances
from photon_ml_tpu.parallel import (
    data_parallel_mesh,
    make_mesh,
    replicate,
    shard_batch,
    shard_coefficients,
    shard_entity_blocks,
)
from photon_ml_tpu.testing import generate_glm_data, generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return data_parallel_mesh(8)


def test_sharded_objective_matches_local(mesh8, rng):
    x, y, _ = generate_glm_data(n=96, d=12, seed=1)
    batch = batch_from_dense(x, y, dtype=jnp.float64)
    obj_local = GLMObjective(loss=LOGISTIC, batch=batch, l2=0.5)
    w = jnp.asarray(rng.normal(size=12))

    sharded = shard_batch(batch, mesh8)
    obj_sharded = GLMObjective(loss=LOGISTIC, batch=sharded, l2=0.5)
    w_rep = replicate(w, mesh8)

    v1, g1 = obj_local.value_and_grad(w)
    v2, g2 = jax.jit(obj_sharded.value_and_grad)(w_rep)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-12)
    # Hv too (the TRON path)
    hv1 = obj_local.hessian_vector(w, w)
    hv2 = jax.jit(obj_sharded.hessian_vector)(w_rep, w_rep)
    np.testing.assert_allclose(np.asarray(hv1), np.asarray(hv2), rtol=1e-11)


def test_sharded_padding_is_invisible(mesh8):
    # 100 rows don't divide 8; padding adds zero-weight rows
    x, y, _ = generate_glm_data(n=100, d=6, seed=2)
    batch = batch_from_dense(x, y, dtype=jnp.float64)
    sharded = shard_batch(batch, mesh8)
    assert sharded.n_rows == 104
    w = jnp.ones(6, jnp.float64)
    v1 = GLMObjective(loss=LOGISTIC, batch=batch).value(w)
    v2 = GLMObjective(loss=LOGISTIC, batch=sharded).value(replicate(w, mesh8))
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-12)


def test_sharded_training_matches_local(mesh8):
    x, y, _ = generate_glm_data(n=160, d=10, seed=3)
    batch = batch_from_dense(x, y, dtype=jnp.float64)
    obj = GLMObjective(loss=LOGISTIC, batch=batch, l2=1.0)
    w0 = jnp.zeros(10, jnp.float64)
    lt, gt = abs_tolerances(obj.value_and_grad, w0, 1e-10)
    res_local = solve_lbfgs(obj.value_and_grad, w0, lt, gt, max_iterations=100)

    sharded = shard_batch(batch, mesh8)
    obj_s = GLMObjective(loss=LOGISTIC, batch=sharded, l2=1.0)
    res_shard = solve_lbfgs(
        obj_s.value_and_grad, replicate(w0, mesh8), lt, gt, max_iterations=100
    )
    np.testing.assert_allclose(
        np.asarray(res_shard.coefficients), np.asarray(res_local.coefficients), atol=1e-9
    )


def test_feature_dim_sharding(mesh8):
    """Tensor-sharded coefficients (2x4 mesh): the huge-d regime where the
    gradient all-reduce becomes a reduce-scatter over the model axis."""
    mesh = make_mesh(n_data=2, n_model=4)
    x, y, _ = generate_glm_data(n=64, d=16, seed=4)
    batch = batch_from_dense(x, y, dtype=jnp.float64)
    sharded = shard_batch(batch, mesh, shard_features_dim=True)
    w = jnp.asarray(np.linspace(-1, 1, 16))
    w_sharded = shard_coefficients(w, mesh)
    obj_local = GLMObjective(loss=LOGISTIC, batch=batch)
    obj_shard = GLMObjective(loss=LOGISTIC, batch=sharded)
    v1, g1 = obj_local.value_and_grad(w)
    v2, g2 = jax.jit(obj_shard.value_and_grad)(w_sharded)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-11)


def test_entity_sharded_random_effect_training(mesh8):
    """Full GAME coordinate on entity blocks sharded across 8 devices must
    reproduce the unsharded result exactly."""
    data = generate_mixed_effect_data(
        n=800, d_fixed=6, re_specs={"userId": (32, 4)}, seed=11
    )
    raw = mixed_data_to_raw_dataset(data)
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-9, max_iterations=100),
        regularization=RegularizationContext("L2"),
        reg_weight=1.0,
    )
    ds = build_random_effect_dataset(
        raw, "per-user", "userShard", "userId", dtype=jnp.float64,
        pad_entities_to_multiple=8,
    )
    coord = RandomEffectCoordinate(dataset=ds, task="logistic_regression", config=cfg)
    m_local, _ = coord.train(None, None)

    ds_sharded = dataclasses.replace(ds, blocks=shard_entity_blocks(ds.blocks, mesh8))
    coord_s = RandomEffectCoordinate(
        dataset=ds_sharded, task="logistic_regression", config=cfg
    )
    m_shard, _ = coord_s.train(None, None)
    np.testing.assert_allclose(
        np.asarray(m_shard.coef_values), np.asarray(m_local.coef_values), atol=1e-8
    )


def test_full_game_descent_on_mesh(mesh8):
    """Fixed effect data-parallel + random effect entity-parallel, 2 CD
    iterations on the mesh; scores must match the single-device run."""
    data = generate_mixed_effect_data(
        n=640, d_fixed=6, re_specs={"userId": (16, 3)}, seed=13
    )
    raw = mixed_data_to_raw_dataset(data)
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-8, max_iterations=100),
        regularization=RegularizationContext("L2"),
        reg_weight=1.0,
    )

    def run(sharded: bool):
        fe_ds = build_fixed_effect_dataset(raw, "global", "global", dtype=jnp.float64)
        re_ds = build_random_effect_dataset(
            raw, "per-user", "userShard", "userId", dtype=jnp.float64,
            pad_entities_to_multiple=8,
        )
        if sharded:
            fe_ds = dataclasses.replace(fe_ds, batch=shard_batch(fe_ds.batch, mesh8))
            re_ds = dataclasses.replace(
                re_ds, blocks=shard_entity_blocks(re_ds.blocks, mesh8)
            )
        coords = {
            "global": FixedEffectCoordinate(
                dataset=fe_ds, task="logistic_regression", config=cfg
            ),
            "per-user": RandomEffectCoordinate(
                dataset=re_ds, task="logistic_regression", config=cfg
            ),
        }
        return CoordinateDescent(coords, n_iterations=2).run()

    r_local = run(False)
    r_shard = run(True)
    np.testing.assert_allclose(
        np.asarray(r_shard.model["global"].model.coefficients.means),
        np.asarray(r_local.model["global"].model.coefficients.means),
        atol=1e-7,
    )
    np.testing.assert_allclose(
        np.asarray(r_shard.model["per-user"].coef_values),
        np.asarray(r_local.model["per-user"].coef_values),
        atol=1e-7,
    )
