"""Training-report subsystem tests: diagnostics oracles, the golden
report.json schema, jax-free rendering (subprocess with a poisoned jax on
sys.path), and the slow `cli train --report-out` -> `cli report`
rebuild-identity end-to-end."""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu.cli import report as report_cli
from photon_ml_tpu.obs import diagnostics
from photon_ml_tpu.obs import report as report_mod

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- oracles


def test_coefficient_summary_oracle():
    values = [0.0, 1.0, -2.0, 0.5]
    names = ["a", "b", "c", "d"]
    s = diagnostics.coefficient_summary(values, names, n_features_total=10, top_k=2)
    assert s["n_recorded"] == 4 and s["n_nonzero"] == 3
    assert s["n_features_total"] == 10
    assert s["sparsity"] == pytest.approx(0.7)
    assert s["l1_norm"] == pytest.approx(3.5)
    assert s["l2_norm"] == pytest.approx(math.sqrt(1 + 4 + 0.25))
    assert s["max_abs"] == pytest.approx(2.0)
    assert s["quantiles"]["p0"] == pytest.approx(-2.0)
    assert s["quantiles"]["p100"] == pytest.approx(1.0)
    assert s["quantiles"]["p50"] == pytest.approx(0.25)
    # top-k by |weight|, stable order, truncated to k
    assert s["top_features"] == [
        {"feature": "c", "weight": -2.0},
        {"feature": "b", "weight": 1.0},
    ]


def test_coefficient_summary_empty_and_nameless():
    s = diagnostics.coefficient_summary([])
    assert s["n_recorded"] == 0 and s["l2_norm"] == 0.0
    assert s["top_features"] == []
    # no names -> no top_features even with values
    assert diagnostics.coefficient_summary([1.0])["top_features"] == []


def test_shrinkage_summary_oracle():
    """Hand-computed log2 binning: bin = floor(log2(count)), count 0 has
    its own bin; mean/min/max per bin."""
    norms = [1.0, 2.0, 3.0, 4.0, 5.0]
    counts = [1, 2, 3, 5, 0]
    s = diagnostics.shrinkage_summary(norms, counts)
    assert s["n_entities"] == 5
    assert s["norm_quantiles"]["p50"] == pytest.approx(3.0)
    assert [h["support"] for h in s["histogram"]] == ["0", "[1,2)", "[2,4)", "[4,8)"]
    by_bin = {h["support"]: h for h in s["histogram"]}
    assert by_bin["0"]["n_entities"] == 1
    assert by_bin["0"]["mean_norm"] == pytest.approx(5.0)
    assert by_bin["[2,4)"]["n_entities"] == 2
    assert by_bin["[2,4)"]["mean_norm"] == pytest.approx(2.5)
    assert by_bin["[2,4)"]["min_norm"] == pytest.approx(2.0)
    assert by_bin["[2,4)"]["max_norm"] == pytest.approx(3.0)
    with pytest.raises(ValueError):
        diagnostics.shrinkage_summary([1.0], [1, 2])


def test_gauge_trajectories_align_with_none_gaps():
    def g(name, value, **labels):
        return {"name": name, "kind": "gauge", "labels": labels, "value": value}

    snaps = [
        [g("photon_cd_accepted_loss", 5.0, coordinate="global")],
        [
            g("photon_cd_accepted_loss", 4.0, coordinate="global"),
            g("photon_cd_accepted_loss", 9.0, coordinate="per-user"),
        ],
    ]
    t = diagnostics.gauge_trajectories(snaps, "photon_cd_accepted_loss", "coordinate")
    assert t == {"global": [5.0, 4.0], "per-user": [None, 9.0]}


def test_iter_metric_snapshots_tolerates_torn_lines():
    lines = [
        json.dumps({"type": "metrics", "metrics": [{"name": "x"}]}),
        '{"type": "span", "name": "cd.sweep"}',
        '{"type": "metrics", "metr',  # torn trailing line from a crash
    ]
    snaps = list(diagnostics.iter_metric_snapshots(lines))
    assert snaps == [[{"name": "x"}]]


def test_bench_diff_oracle():
    old = {"quadrants": {"fe": {"wall_s": 2.0, "label": "x"}, "re": {"wall_s": 1.0}}}
    new = {"quadrants": {"fe": {"wall_s": 3.0, "label": "y"}}}
    d = report_mod.bench_diff(old, new)
    assert set(d) == {"quadrants.fe.wall_s"}
    assert d["quadrants.fe.wall_s"]["delta_pct"] == pytest.approx(50.0)


def test_sparkline_svg():
    svg = report_mod.sparkline_svg([1.0, None, 3.0, 2.0])
    assert svg.startswith("<svg") and "polyline" in svg
    # fewer than 2 finite points: placeholder box, no polyline
    placeholder = report_mod.sparkline_svg([1.0])
    assert "n/a" in placeholder and "polyline" not in placeholder


# ---------------------------------------------------------------- fixtures


def _gauge(name, value, **labels):
    return {"name": name, "kind": "gauge", "help": "", "labels": labels,
            "value": value}


def _final_snapshot():
    return [
        _gauge("photon_cd_accepted_loss", 4.0, coordinate="global"),
        _gauge("photon_cd_final_loss", 4.0, coordinate="global"),
        _gauge("photon_cd_update_iterations", 7.0, coordinate="global"),
        _gauge("photon_validation_metric", 0.71, metric="AUC", coordinate="global"),
        {"name": "photon_jax_compile_seconds", "kind": "summary", "help": "",
         "labels": {"event": "jit_fn"}, "sum": 1.25,
         "stat": {"count": 2, "mean": 0.625, "stdev": 0.1, "max": 1.0,
                  "min": 0.25}},
        _gauge("photon_stream_budget_bytes", 1024.0, site="fe.train"),
        _gauge("photon_stream_actual_slice_bytes", 256.0, site="fe.train"),
        _gauge("photon_stream_budget_headroom_bytes", 512.0, site="fe.train"),
        _gauge("photon_mem_host_rss_bytes", 1000.0),
        _gauge("photon_mem_host_peak_rss_bytes", 2000.0),
        _gauge("photon_mem_device_bytes_in_use", 300.0, device="0"),
        _gauge("photon_mem_device_peak_bytes_in_use", 400.0, device="0"),
        _gauge("photon_mem_device_bytes_limit", 4096.0, device="0"),
    ]


def _write_model_fixture(model_dir):
    """A saved-model layout written without jax: one fixed effect, one
    random effect with three entities."""
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_AVRO

    def rec(model_id, triples):
        return {
            "modelId": model_id,
            "modelClass": None,
            "means": [{"name": n, "term": t, "value": v} for n, t, v in triples],
            "variances": None,
            "lossFunction": None,
        }

    os.makedirs(model_dir, exist_ok=True)
    with open(os.path.join(model_dir, "model-metadata.json"), "w") as f:
        json.dump({"modelType": "LOGISTIC_REGRESSION"}, f)

    fe = os.path.join(model_dir, "fixed-effect", "global")
    os.makedirs(os.path.join(fe, "coefficients"), exist_ok=True)
    with open(os.path.join(fe, "id-info"), "w") as f:
        f.write("globalShard\n")
    write_avro_file(
        os.path.join(fe, "coefficients", "part-00000.avro"),
        BAYESIAN_LINEAR_MODEL_AVRO,
        [rec("global", [("f0", "", 2.0), ("f1", "t", -1.0), ("f2", "", 0.5)])],
    )

    re_ = os.path.join(model_dir, "random-effect", "per-user")
    os.makedirs(os.path.join(re_, "coefficients"), exist_ok=True)
    with open(os.path.join(re_, "id-info"), "w") as f:
        f.write("userId\nuserShard\n")
    write_avro_file(
        os.path.join(re_, "coefficients", "part-00000.avro"),
        BAYESIAN_LINEAR_MODEL_AVRO,
        [
            rec("u1", [("f0", "", 3.0), ("f1", "", 4.0)]),
            rec("u2", [("f0", "", 1.0)]),
            rec("u3", []),
        ],
    )


def _make_artifacts(root):
    """A complete synthetic artifacts tree `cli report` can discover."""
    os.makedirs(root, exist_ok=True)
    final = _final_snapshot()
    with open(os.path.join(root, "run_summary.json"), "w") as f:
        json.dump(
            {
                "total_wall_seconds": 12.5,
                "task": "logistic_regression",
                "best": {"reg_weights": {"global": 1.0}, "metrics": {"AUC": 0.71}},
                "coordinates": {
                    "global": {
                        "iterations": {"count": 2, "mean": 7.0, "stdev": 0.0,
                                       "max": 7.0, "min": 7.0},
                        "convergence_reasons": {"GRADIENT_CONVERGED": 2},
                        "rejections": 1,
                    }
                },
                "metrics": final,
                "plan": {
                    "coordinates": [
                        {"name": "global", "kind": "fixed-effect",
                         "layout": "auto", "feature_dtype": None,
                         "residency": "streamed",
                         "sharding": "host-sharded rows (streamed slices)",
                         "pipelined": True, "hbm_budget_mb": 0,
                         "geometry": {}, "notes": []},
                    ],
                    "mesh_axes": {"data": 8, "model": 1},
                    "n_processes": 2,
                    "pipeline_depth": 2,
                    "trial_lanes": 1,
                    "normalization": "NONE",
                    "distributed": True,
                },
                "memory": {"host": {"rss_bytes": 1000, "peak_rss_bytes": 2000}},
                "timeline": {
                    "n_sweeps": 2,
                    "sweeps": [{"overlap_factor": 0.0}, {"overlap_factor": 0.25}],
                    "total": {"wall_seconds": 10.0, "phases": {"solve": 8.0},
                              "critical_path_seconds": 9.0, "other_seconds": 1.0,
                              "sum_of_phases_seconds": 12.0,
                              "overlap_factor": 0.25},
                },
            },
            f,
        )
    with open(os.path.join(root, "metrics.jsonl"), "w") as f:
        first = [
            _gauge("photon_cd_accepted_loss", 5.0, coordinate="global"),
            _gauge("photon_validation_metric", 0.65, metric="AUC",
                   coordinate="global"),
        ]
        f.write(json.dumps({"type": "metrics", "metrics": first}) + "\n")
        f.write(json.dumps({"type": "metrics", "metrics": final}) + "\n")
    with open(os.path.join(root, "training-summary.json"), "w") as f:
        json.dump({"task": "logistic_regression",
                   "best": {"reg_weights": {"global": 1.0}}}, f)
    _write_model_fixture(os.path.join(root, "models", "best"))
    idx = os.path.join(root, "index")
    os.makedirs(idx, exist_ok=True)
    with open(os.path.join(idx, "_index-globalShard-meta.json"), "w") as f:
        json.dump({"shard": "globalShard", "numPartitions": 1, "size": 6}, f)
    ck = os.path.join(root, "ckpt", "boundary-000001")
    os.makedirs(ck, exist_ok=True)
    with open(os.path.join(ck, "MANIFEST.json"), "w") as f:
        json.dump({"step": 1, "iteration": 0, "coordinate": "global",
                   "bytes": 123, "sha256": "0" * 64}, f)
    with open(os.path.join(root, "bench-progress.jsonl"), "w") as f:
        f.write(json.dumps({"ts": 1.0, "type": "bench_diff", "tolerance": 0.2,
                            "regressed": [],
                            "series": {"fe.wall_s": {"old": 2.0, "new": 1.8,
                                                     "delta_pct": -10.0}}})
                + "\n")
    return root


# ---------------------------------------------------------------- golden schema


def test_report_json_golden_schema(tmp_path):
    """Pin the report.json schema: key sets at every level a consumer would
    bind to. Additions require a deliberate schema_version discussion."""
    root = _make_artifacts(str(tmp_path / "artifacts"))
    doc = report_cli.run([root, "--out", str(tmp_path / "rep")])

    assert doc["schema_version"] == 3
    assert set(doc) == {
        "schema_version", "task", "best", "models", "convergence",
        "performance", "plan", "memory", "checkpoints", "bench", "flight",
    }
    assert doc["task"] == "logistic_regression"

    # v3: flight-recorder postmortems ride along (none in these artifacts)
    assert doc["flight"] == []

    # v2: the resolved execution plan rides along verbatim from
    # run_summary.json (None when the run predates the planner)
    plan = doc["plan"]
    assert plan["n_processes"] == 2 and plan["mesh_axes"] == {"data": 8,
                                                             "model": 1}
    (cp,) = plan["coordinates"]
    assert cp["residency"] == "streamed"
    assert cp["sharding"] == "host-sharded rows (streamed slices)"

    assert set(doc["models"]) == {"best"}
    model = doc["models"]["best"]
    assert set(model) == {"metadata", "coordinates"}
    assert set(model["coordinates"]) == {"global", "per-user"}
    fe = model["coordinates"]["global"]
    assert set(fe) == {"type", "feature_shard", "coefficients"}
    assert fe["type"] == "fixed" and fe["feature_shard"] == "globalShard"
    assert set(fe["coefficients"]) == {
        "n_nonzero", "n_recorded", "n_features_total", "sparsity", "l1_norm",
        "l2_norm", "max_abs", "quantiles", "top_features",
    }
    assert set(fe["coefficients"]["quantiles"]) == {"p0", "p25", "p50", "p75",
                                                    "p100"}
    # sparsity uses the feature-index size: 3 recorded of 6 total
    assert fe["coefficients"]["sparsity"] == pytest.approx(0.5)
    assert fe["coefficients"]["top_features"][0] == {"feature": "f0",
                                                     "weight": 2.0}
    re_ = model["coordinates"]["per-user"]
    assert set(re_) == {"type", "feature_shard", "random_effect_type",
                        "n_entities", "coefficients", "shrinkage"}
    assert re_["type"] == "random" and re_["n_entities"] == 3
    assert set(re_["shrinkage"]) == {"n_entities", "norm_quantiles",
                                     "histogram"}
    assert [h["support"] for h in re_["shrinkage"]["histogram"]] == \
        ["0", "[1,2)", "[2,4)"]
    assert set(re_["shrinkage"]["histogram"][0]) == {
        "support", "n_entities", "mean_norm", "min_norm", "max_norm",
    }

    conv = doc["convergence"]
    assert set(conv) == {"coordinates", "validation_trajectories",
                         "n_metric_flushes"}
    assert conv["n_metric_flushes"] == 2
    g = conv["coordinates"]["global"]
    assert g["accepted_loss_trajectory"] == [5.0, 4.0]
    assert g["iterations_trajectory"] == [None, 7.0]
    assert g["final_loss"] == pytest.approx(4.0)
    assert g["rejections"] == 1
    assert conv["validation_trajectories"]["AUC"] == [0.65, 0.71]

    perf = doc["performance"]
    assert set(perf) == {"total_wall_seconds", "aborted", "compile_seconds",
                         "timeline", "streaming"}
    assert perf["aborted"] is False
    assert perf["compile_seconds"] == pytest.approx(1.25)
    assert set(perf["timeline"]) == {"n_sweeps", "total",
                                     "overlap_factor_per_sweep"}
    assert perf["timeline"]["overlap_factor_per_sweep"] == [0.0, 0.25]
    assert perf["streaming"]["fe.train"]["budget_utilization"] == \
        pytest.approx(0.5)

    assert doc["memory"]["host"]["rss_bytes"] == 1000
    assert doc["checkpoints"] == [{"step": 1, "iteration": 0,
                                   "coordinate": "global", "bytes": 123}]
    assert len(doc["bench"]["progress"]) == 1

    # files landed and report.json round-trips to the returned doc
    out = str(tmp_path / "rep")
    with open(os.path.join(out, "report.json")) as f:
        assert json.load(f) == json.loads(json.dumps(doc, default=float))
    with open(os.path.join(out, "report.html")) as f:
        html = f.read()
    assert html.lower().startswith("<!doctype html>") and "<svg" in html


def test_report_discovers_flight_dumps(tmp_path):
    """A flight-recorder postmortem in the artifacts tree lands as a
    doc["flight"] row (and an HTML section), ordered by trigger time."""
    root = _make_artifacts(str(tmp_path / "artifacts"))
    flight_dir = os.path.join(root, "flight")
    os.makedirs(flight_dir, exist_ok=True)
    for seq, (kind, t) in enumerate(
        [("shed_spike", 200.0), ("crash", 100.0)]
    ):
        with open(
            os.path.join(flight_dir, f"flight-{kind}-{seq:04d}.json"), "w"
        ) as f:
            json.dump({
                "trigger": {"kind": kind, "detail": "drill", "unix_time": t},
                "window_seconds": 30.0,
                "identity": {"process_index": 0, "replica": None, "host": "h"},
                "events": [{"type": "span", "name": "x"}],
                "metrics": [],
            }, f)
    doc = report_cli.run([root, "--out", str(tmp_path / "rep")])
    assert [row["trigger"] for row in doc["flight"]] == ["crash", "shed_spike"]
    row = doc["flight"][0]
    assert row["detail"] == "drill" and row["n_events"] == 1
    assert row["path"].startswith("flight/")
    html = open(os.path.join(str(tmp_path / "rep"), "report.html")).read()
    assert "Flight recorder" in html


def test_report_cli_rejects_empty_dir(tmp_path):
    with pytest.raises(SystemExit):
        report_cli.run([str(tmp_path / "empty")])


def test_report_cli_bench_pair_required_together(tmp_path):
    root = _make_artifacts(str(tmp_path / "a"))
    with pytest.raises(SystemExit):
        report_cli.run([root, "--bench-baseline", "x.json"])


def test_report_cli_bench_diff_section(tmp_path):
    root = _make_artifacts(str(tmp_path / "a"))
    old = str(tmp_path / "old.json")
    new = str(tmp_path / "new.json")
    with open(old, "w") as f:
        json.dump({"quadrants": {"fe": {"wall_s": 2.0}}}, f)
    with open(new, "w") as f:
        json.dump({"quadrants": {"fe": {"wall_s": 1.0}}}, f)
    doc = report_cli.run([root, "--out", str(tmp_path / "rep"),
                          "--bench-baseline", old, "--bench-candidate", new])
    assert doc["bench"]["diff"]["quadrants.fe.wall_s"]["delta_pct"] == \
        pytest.approx(-50.0)


# ---------------------------------------------------------------- jax-free


def test_report_cli_runs_with_poisoned_jax(tmp_path):
    """`cli report` must work in a process where importing jax raises — the
    acceptance criterion for the jax-free report path. The rebuilt
    report.json must equal the one built with jax importable."""
    root = _make_artifacts(str(tmp_path / "artifacts"))
    ref = report_cli.run([root, "--out", str(tmp_path / "ref")])

    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('jax is poisoned for this test')\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(poison), REPO_ROOT, env.get("PYTHONPATH", "")]
    )
    out = str(tmp_path / "rebuilt")
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli.report", root, "--out", out],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    # the poison actually poisons: a control subprocess importing jax fails
    control = subprocess.run(
        [sys.executable, "-c", "import jax"], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert control.returncode != 0

    with open(os.path.join(out, "report.json")) as f:
        rebuilt = json.load(f)
    assert rebuilt == json.loads(json.dumps(ref, default=float))
    with open(os.path.join(out, "report.html")) as f:
        assert "<svg" in f.read()


def test_obs_and_io_import_without_jax(tmp_path):
    """The report-path modules import with jax poisoned (lint rule R8's
    runtime counterpart)."""
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text("raise ImportError('poisoned')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(poison), REPO_ROOT, env.get("PYTHONPATH", "")]
    )
    src = (
        "import photon_ml_tpu.obs as obs\n"
        "import photon_ml_tpu.obs.report, photon_ml_tpu.obs.diagnostics\n"
        "import photon_ml_tpu.obs.memory, photon_ml_tpu.cli.report\n"
        "from photon_ml_tpu.io import read_avro_file, IndexMap\n"
        "print('ok')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", src], env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "ok"


# ---------------------------------------------------------------- memory block


def test_memory_block_from_snapshot():
    from photon_ml_tpu.obs.memory import memory_block

    block = memory_block(_final_snapshot())
    assert block["host"] == {"rss_bytes": 1000, "peak_rss_bytes": 2000}
    assert block["devices"]["0"] == {
        "bytes_in_use": 300, "peak_bytes_in_use": 400, "bytes_limit": 4096,
    }
    assert block["streaming"]["fe.train"]["hbm_budget_bytes"] == 1024
    assert memory_block([]) == {}


def test_sample_memory_host_and_peak_monotone():
    from photon_ml_tpu.obs.memory import sample_memory
    from photon_ml_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    sample_memory(reg)
    snap = {m["name"]: m for m in reg.snapshot()}
    assert snap["photon_mem_host_rss_bytes"]["value"] > 0
    peak1 = snap["photon_mem_host_peak_rss_bytes"]["value"]
    assert peak1 >= snap["photon_mem_host_rss_bytes"]["value"] * 0  # present
    # a second sample can only raise the peak
    sample_memory(reg)
    snap2 = {m["name"]: m for m in reg.snapshot()}
    assert snap2["photon_mem_host_peak_rss_bytes"]["value"] >= peak1


# ---------------------------------------------------------------- slow e2e


@pytest.mark.slow
def test_train_report_rebuild_identity(tmp_path):
    """Acceptance criterion: `cli train --report-out` writes report.json +
    report.html, and `cli report <artifacts-root>` rebuilds a byte-identical
    report.json from the artifacts alone."""
    from photon_ml_tpu.cli import train
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing import (
        generate_game_records, generate_mixed_effect_data,
    )

    data = generate_mixed_effect_data(
        n=300, d_fixed=4, re_specs={"userId": (8, 2)}, seed=7
    )
    recs = generate_game_records(data)
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"] + [
            {"name": "userFeatures",
             "type": {"type": "array", "items": "FeatureAvro"}, "default": []}
        ],
    }
    train_p = str(tmp_path / "train.avro")
    write_avro_file(train_p, schema, recs)

    root = tmp_path / "run"
    train.run([
        "--input-data", train_p,
        "--task", "logistic_regression",
        "--feature-shard", "name=globalShard,bags=features",
        "--feature-shard", "name=userShard,bags=userFeatures",
        "--coordinate",
        "name=global,shard=globalShard,optimizer=LBFGS,reg.type=L2,"
        "reg.weights=1",
        "--coordinate",
        "name=per-user,shard=userShard,re.type=userId,reg.type=L2,"
        "reg.weights=1",
        "--coordinate-descent-iterations", "2",
        "--output-dir", str(root / "out"),
        "--metrics-out", str(root / "metrics"),
        "--report-out", str(root / "report"),
    ])

    rep = root / "report"
    assert (rep / "report.html").exists()
    with open(rep / "report.json", "rb") as f:
        trained_bytes = f.read()
    trained = json.loads(trained_bytes)
    assert trained["task"] == "logistic_regression"
    assert set(trained["models"]) == {"best"}
    assert set(trained["models"]["best"]["coordinates"]) == \
        {"global", "per-user"}
    g = trained["convergence"]["coordinates"]["global"]
    assert len(g["accepted_loss_trajectory"]) == \
        trained["convergence"]["n_metric_flushes"]
    assert any(v is not None for v in g["accepted_loss_trajectory"])
    assert trained["memory"]["host"]["rss_bytes"] > 0
    assert trained["performance"]["aborted"] is False

    report_cli.run([str(root), "--out", str(tmp_path / "rebuilt")])
    with open(tmp_path / "rebuilt" / "report.json", "rb") as f:
        rebuilt_bytes = f.read()
    assert rebuilt_bytes == trained_bytes
