"""Hyperparameter tuning tests: GP regression sanity, slice sampler, EI,
rescaling, and end-to-end Bayesian search beating random search on a known
function (mirrors the reference's hyperparameter/** unit suites)."""

import numpy as np
import pytest

from photon_ml_tpu.tuning import (
    BayesianTuner,
    DummyTuner,
    GaussianProcessEstimator,
    GaussianProcessModel,
    GaussianProcessSearch,
    HyperparameterConfig,
    Matern52,
    ParamRange,
    RBF,
    RandomSearch,
    expected_improvement,
    get_tuner,
    slice_sample,
)


def test_kernels_psd(rng):
    x = rng.uniform(size=(20, 3))
    for kern in (RBF(lengthscale=np.asarray([0.5])), Matern52(lengthscale=np.asarray([0.5]))):
        k = kern.cov(x)
        np.testing.assert_allclose(k, k.T, atol=1e-12)
        w = np.linalg.eigvalsh(k)
        assert w.min() > -1e-9
        # k(x,x) = amplitude on the diagonal
        np.testing.assert_allclose(np.diag(k), kern.amplitude, rtol=1e-10)


def test_gp_interpolates_noise_free(rng):
    x = rng.uniform(size=(12, 1)) * 4
    y = np.sin(x[:, 0])
    gp = GaussianProcessModel(Matern52(noise=1e-8, lengthscale=np.asarray([1.0])), x, y)
    mu, var = gp.predict(x)
    np.testing.assert_allclose(mu, y, atol=1e-4)
    assert var.max() < 1e-4
    # between points, prediction approximates sin with small error
    xs = np.linspace(0.2, 3.8, 25)[:, None]
    mu2, _ = gp.predict(xs)
    assert np.abs(mu2 - np.sin(xs[:, 0])).max() < 0.1


def test_gp_estimator_fits_reasonably(rng):
    x = rng.uniform(size=(25, 2))
    y = np.sin(3 * x[:, 0]) + 0.5 * x[:, 1] + 0.05 * rng.normal(size=25)
    post = GaussianProcessEstimator(seed=1).fit(x, y)
    mu, var = post.predict(x)
    assert np.corrcoef(mu, y)[0, 1] > 0.95


def test_slice_sampler_matches_gaussian():
    rng = np.random.default_rng(3)
    logp = lambda x: float(-0.5 * ((x[0] - 2.0) / 1.5) ** 2)
    samples = slice_sample(logp, np.zeros(1), 3000, rng, burn_in=50)
    assert abs(samples.mean() - 2.0) < 0.15
    assert abs(samples.std() - 1.5) < 0.2


def test_expected_improvement_properties():
    # lower mean -> higher EI; higher var -> higher EI at equal mean
    ei = expected_improvement(0.0, np.asarray([-1.0, 0.0, 1.0]), np.asarray([1.0, 1.0, 1.0]))
    assert ei[0] > ei[1] > ei[2]
    ei2 = expected_improvement(0.0, np.asarray([1.0, 1.0]), np.asarray([0.01, 4.0]))
    assert ei2[1] > ei2[0]


def test_rescaling_round_trip():
    cfg = HyperparameterConfig(
        params=[
            ParamRange("lambda", 1e-4, 1e4, transform="LOG"),
            ParamRange("alpha", 0.0, 1.0),
            ParamRange("depth", 1, 10, discrete=True),
        ]
    )
    native = cfg.scale_up(np.asarray([0.5, 0.25, 0.47]))
    np.testing.assert_allclose(native[0], 1.0, rtol=1e-10)  # log midpoint of 1e-4..1e4
    assert native[1] == 0.25
    assert native[2] == round(native[2])
    unit = cfg.scale_down(native)
    np.testing.assert_allclose(unit[:2], [0.5, 0.25], atol=1e-12)
    # JSON round trip
    cfg2 = HyperparameterConfig.from_json(cfg.to_json())
    assert cfg2 == cfg


def _quadratic_eval(x):
    # minimum at (0.3, 0.7)
    v = (x[0] - 0.3) ** 2 + (x[1] - 0.7) ** 2
    return float(v), None


def test_random_search_runs():
    rs = RandomSearch(2, _quadratic_eval, seed=5)
    obs = rs.find(16)
    assert len(obs) == 16
    assert min(o.value for o in obs) < 0.15


def test_bayesian_beats_random():
    n_iters = 25
    rs_best = min(o.value for o in RandomSearch(2, _quadratic_eval, seed=11).find(n_iters))
    gp_best = min(
        o.value
        for o in GaussianProcessSearch(2, _quadratic_eval, seed=11).find(n_iters)
    )
    assert gp_best <= rs_best + 1e-9
    assert gp_best < 0.01


def test_bayesian_with_discrete_dim():
    def ev(x):
        return float((x[0] - 0.5) ** 2 + x[1]), None

    gs = GaussianProcessSearch(2, ev, discrete_params={1: 3}, seed=2)
    obs = gs.find(12)
    vals = {round(o.candidate[1], 6) for o in obs}
    assert vals <= {0.0, 0.5, 1.0}


def test_tuner_factory():
    assert isinstance(get_tuner("DUMMY"), DummyTuner)
    assert isinstance(get_tuner("BAYESIAN"), BayesianTuner)
    assert get_tuner("DUMMY").search(5, 2, _quadratic_eval) == []
    obs = get_tuner("RANDOM").search(4, 2, _quadratic_eval)
    assert len(obs) == 4
    with pytest.raises(ValueError):
        get_tuner("nope")


# --- serialization / shrink (HyperparameterSerialization.scala:27-136,
#     ShrinkSearchRange.scala:40-108) ---


def test_config_from_json_reference_shape():
    from photon_ml_tpu.tuning import config_from_json

    mode, hp = config_from_json(
        '{"tuning_mode": "BAYESIAN", "variables": {'
        '"global.reg_weight": {"type": "DOUBLE", "min": 1e-4, "max": 1e4,'
        ' "transform": "LOG"},'
        '"alpha": {"type": "DOUBLE", "min": 0.0, "max": 1.0},'
        '"k": {"type": "INT", "min": 0, "max": 8, "transform": "SQRT"}}}'
    )
    assert mode == "BAYESIAN"
    names = [p.name for p in hp.params]
    assert names == ["global.reg_weight", "alpha", "k"]
    assert hp.params[0].transform == "LOG"
    assert not hp.params[0].discrete
    assert hp.params[2].discrete and hp.params[2].transform == "SQRT"
    # INT -> discrete dims map (index -> cardinality)
    assert hp.discrete_dims() == {2: 9}
    # unknown mode -> NONE; missing variables -> error; bad transform -> error
    mode2, _ = config_from_json('{"tuning_mode": "ATLAS", "variables": {}}')
    assert mode2 == "NONE"
    with pytest.raises(ValueError):
        config_from_json('{"tuning_mode": "RANDOM"}')
    with pytest.raises(ValueError):
        config_from_json(
            '{"variables": {"a": {"min": 0, "max": 1, "transform": "EXP"}}}'
        )


def test_sqrt_transform_round_trip():
    from photon_ml_tpu.tuning.rescaling import ParamRange

    p = ParamRange(name="x", min=1.0, max=100.0, transform="SQRT")
    for unit in (0.0, 0.25, 0.5, 1.0):
        native = p.scale_up(unit)
        assert 1.0 <= native <= 100.0
        np.testing.assert_allclose(p.scale_down(native), unit, atol=1e-12)
    # sqrt-space midpoint: sqrt ranges 1..10, mid 5.5 -> 30.25
    np.testing.assert_allclose(p.scale_up(0.5), 30.25)


def test_prior_json_round_trip_with_defaults():
    from photon_ml_tpu.tuning import prior_from_json, prior_to_json

    names = ["a", "b"]
    priors = [(np.array([0.1, 2.0]), 0.75), (np.array([10.0, 4.0]), 0.9)]
    text = prior_to_json(names, priors)
    back = prior_from_json(text, {}, names)
    for (x0, v0), (x1, v1) in zip(priors, back):
        np.testing.assert_allclose(x0, x1)
        assert v0 == v1
    # missing field falls back to default; no default -> KeyError
    partial = '{"records": [{"a": "1.0", "evaluationValue": "0.5"}]}'
    vecs = prior_from_json(partial, {"b": 7.0}, names)
    np.testing.assert_allclose(vecs[0][0], [1.0, 7.0])
    with pytest.raises(KeyError):
        prior_from_json(partial, {}, names)


def test_shrink_search_range_bounds():
    from photon_ml_tpu.tuning import get_bounds

    hp = HyperparameterConfig(
        params=[ParamRange(name="lam", min=1e-4, max=1e4, transform="LOG")]
    )
    # evaluation peaks at lam = 1.0 (unit 0.5); GP should shrink around it
    lams = np.array([1e-4, 1e-2, 1.0, 1e2, 1e4, 0.1, 10.0])
    vals = [float(-(np.log10(l)) ** 2) for l in lams]
    from photon_ml_tpu.tuning import prior_to_json

    prior = prior_to_json(["lam"], [(np.array([l]), v) for l, v in zip(lams, vals)])
    lo, hi = get_bounds(hp, prior, radius=0.2, seed=3)
    assert lo.shape == (1,) and hi.shape == (1,)
    # the shrunk range must be inside the original and contain the optimum
    assert 1e-4 <= lo[0] < 1.0 < hi[0] <= 1e4
    # radius 0.2 in unit space = 10^(8*0.4) ~ 1580x range vs original 1e8
    assert hi[0] / lo[0] < 1e7


def test_tuner_accepts_grid_observations():
    """Seeding observations warm-starts the GP path (no cold-start random
    draws once len(observations) > dim)."""
    from photon_ml_tpu.tuning import BayesianTuner, Observation

    calls = []

    def ev(x):
        calls.append(x.copy())
        v = (x[0] - 0.3) ** 2
        return float(v), None

    seeds = [
        Observation(candidate=np.array([u]), value=float((u - 0.3) ** 2))
        for u in (0.05, 0.35, 0.65, 0.95)
    ]
    obs = BayesianTuner().search(6, 1, ev, observations=seeds, seed=7)
    assert len(obs) == 6
    # warm-started GP should concentrate near the seeded optimum
    best = min(o.value for o in obs)
    assert best < 0.01
