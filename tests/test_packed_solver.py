"""Entity-minor packed RE solver parity vs the vmapped per-entity solver.

The packed path (game/coordinate._train_blocks_packed + the batched modes of
optimize/lbfgs.py and optimize/tron.py) solves the same per-entity problems
with the entity axis minor (TPU lane dimension). Same convex objectives, so
both paths must land on the same optimum; the iterate paths may differ
slightly (reduction-order f32 noise, shared-cursor history in batched LBFGS),
hence optimization-level tolerances.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.game.coordinate import _train_blocks, _train_blocks_packed


def _problem(seed=0, E=37, K=12, S=9, active_k=10):
    rng = np.random.default_rng(seed)
    F = rng.normal(size=(E, K, S)).astype(np.float32)
    w_true = rng.normal(size=(E, S)).astype(np.float32)
    logits = np.einsum("eks,es->ek", F, w_true)
    y = (rng.uniform(size=(E, K)) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    off = rng.normal(size=(E, K)).astype(np.float32) * 0.1
    wt = np.ones((E, K), np.float32)
    wt[:, active_k:] = 0.0  # padded rows carry zero weight
    y[:, active_k:] = 0.0
    w0 = np.zeros((E, S), np.float32)
    pm = np.zeros((E, S), np.float32)
    pp = np.ones((E, S), np.float32)
    return F, y, off, wt, w0, pm, pp


@pytest.mark.parametrize(
    "opt,l1",
    [("LBFGS", 0.0), ("TRON", 0.0), ("LBFGS", 0.05)],
    ids=["lbfgs", "tron", "owlqn"],
)
def test_packed_matches_vmapped(opt, l1):
    args = _problem()
    kwargs = dict(
        task="logistic",
        l2=0.1,
        l1=l1,
        optimizer_type=opt,
        tolerance=1e-7,
        max_iterations=80,
        num_corrections=10,
        max_cg_iterations=20,
        max_improvement_failures=5,
    )
    rv = _train_blocks(*args, **kwargs)
    rp = _train_blocks_packed(*args, **kwargs)
    np.testing.assert_allclose(
        np.asarray(rp.coefficients), np.asarray(rv.coefficients), atol=5e-3
    )
    np.testing.assert_allclose(np.asarray(rp.loss), np.asarray(rv.loss), atol=1e-4)
    # per-lane result structure matches
    assert rp.coefficients.shape == rv.coefficients.shape
    assert rp.loss_history.shape == rv.loss_history.shape
    assert rp.iterations.shape == rv.iterations.shape


def test_packed_prior_and_warm_start():
    """Prior-centered L2 (incremental training) and a warm start w0 follow
    the same algebra on both paths."""
    F, y, off, wt, w0, pm, pp = _problem(seed=3)
    rng = np.random.default_rng(7)
    w0 = rng.normal(size=w0.shape).astype(np.float32) * 0.1
    pm = rng.normal(size=pm.shape).astype(np.float32) * 0.2
    pp = (0.5 + rng.uniform(size=pp.shape)).astype(np.float32)
    kwargs = dict(
        task="logistic",
        l2=0.7,
        l1=0.0,
        optimizer_type="LBFGS",
        tolerance=1e-8,
        max_iterations=80,
        num_corrections=10,
        max_cg_iterations=20,
        max_improvement_failures=5,
    )
    rv = _train_blocks(F, y, off, wt, w0, pm, pp, **kwargs)
    rp = _train_blocks_packed(F, y, off, wt, w0, pm, pp, **kwargs)
    np.testing.assert_allclose(
        np.asarray(rp.coefficients), np.asarray(rv.coefficients), atol=5e-3
    )
    np.testing.assert_allclose(np.asarray(rp.loss), np.asarray(rv.loss), atol=1e-4)


def test_batched_lbfgs_gradient_at_optimum():
    """The packed solve's final per-lane gradient norms are small (true
    stationary points, not an artifact of matching a mis-converged twin)."""
    args = _problem(seed=5)
    kwargs = dict(
        task="logistic",
        l2=0.3,
        l1=0.0,
        optimizer_type="LBFGS",
        tolerance=1e-9,
        max_iterations=120,
        num_corrections=10,
        max_cg_iterations=20,
        max_improvement_failures=5,
    )
    rp = _train_blocks_packed(*args, **kwargs)
    gn = np.linalg.norm(np.asarray(rp.gradient), axis=1)
    assert np.all(gn < 1e-2)
    assert np.all(np.asarray(rp.reason) != 0)  # every lane converged
