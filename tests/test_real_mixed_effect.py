"""Real-data mixed-effect regression fixture with captured thresholds.

The reference pins GAME training correctness to a real dataset with captured
metric thresholds ("baseline RMSE capture from an assumed-correct
implementation", GameTrainingDriverIntegTest.scala:47-77, Yahoo! Music).
This repo's equivalent uses the REAL UCI Adult a9a fixture shipped with the
reference (DriverIntegTest/input/a9a + a9a.t, the official test split as the
external anchor).

The mixed-effect structure is derived from the data itself, not synthesized:
a9a's one-hot blocks [19,35) (education, 16 levels) and [40,47) (marital
status, 7 levels) are exact-one-hot in every row; their cross defines 101
REAL entities with genuine skew (counts 1..4845, median 49). The fixture
holds those one-hot blocks OUT of the fixed shard, so group-level signal is
only reachable through the per-group random effects — the same role user ids
play in the reference's Yahoo! Music setup.

Thresholds captured 2026-07-30 on this implementation (f64, CPU):
  fixed-only  test AUC 0.90054
  fixed + RE  test AUC 0.90205   (per-group intercept + age/capital deviations)
Assertions leave a small margin for cross-platform float noise; a real
regression (solver, RE build, scoring) shows up as multiples of the margin.

The RE-lift assertion is pinned to the CAPTURED lift (0.00151) minus a float
noise allowance — not a loose fraction of it — so a partially-broken RE path
(e.g. one that recovers only a third of the captured lift) fails instead of
slipping under a 3x-slack floor. Per-group quality is pinned with the
MultiEvaluator grammar ("AUC:groupId": unweighted mean of per-group AUCs over
the 101 real entities): the mixed model's per-group AUC must stay within
float noise of the fixed model's (per-group deviations must not degrade
within-group ranking) and above a conservative floor — per-group AUC
averages many small skewed groups, so it sits below the pooled value but far
above chance.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.estimators.game_estimator import CoordinateConfig, GameEstimator
from photon_ml_tpu.evaluation import area_under_roc_curve, build_suite
from photon_ml_tpu.game.data import _rows_to_ell
from photon_ml_tpu.game.problem import GLMOptimizationConfig
from photon_ml_tpu.io.data import read_libsvm
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig

A9A = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/a9a"
# discovered exact-one-hot blocks (1-based libsvm cols): education, marital
EDU, MAR = (19, 35), (40, 47)
RE_COLS = list(range(1, 6)) + list(range(72, 83))  # age bucket + capital/hours

# captured 2026-07-30 (see module docstring)
FIXED_AUC_CAPTURED = 0.90054
MIXED_AUC_CAPTURED = 0.90205
MARGIN = 0.003
# RE lift pinned to the captured value minus float noise only; the previous
# floor (0.0005) was ~3x slack and let a mostly-broken RE path pass
CAPTURED_LIFT = MIXED_AUC_CAPTURED - FIXED_AUC_CAPTURED  # 0.00151
LIFT_NOISE = 2e-4  # cross-platform float noise on an AUC delta (f64, n=32561)
# per-group (MultiEvaluator) mean-of-group AUC: conservative floor, see
# module docstring for why this sits below the pooled AUC
GROUPED_AUC_FLOOR = 0.70

pytestmark = pytest.mark.skipif(
    not os.path.exists(A9A), reason="reference a9a fixture not present"
)


def _prep(raw):
    """Derive real entities (education x marital) and split shards: the fixed
    shard excludes the group one-hots; the RE shard carries age/capital
    features plus a per-group intercept."""
    rows, cols, vals = raw.shard_coo["global"]
    n = raw.n_rows
    ent = np.full(n, -1, np.int64)
    for lo, hi, mul in ((EDU[0], EDU[1], 1), (MAR[0], MAR[1], 100)):
        m = (cols >= lo) & (cols < hi) & (vals != 0)
        ent[rows[m]] += (cols[m] - lo + 1) * mul
    ids = np.array([f"g{e}" for e in ent], object)
    group_cols = list(range(*EDU)) + list(range(*MAR))
    keepf = ~np.isin(cols, group_cols)
    remap = {c: i for i, c in enumerate(RE_COLS)}
    keep = np.isin(cols, RE_COLS)
    rr = np.concatenate([rows[keep], np.arange(n)])
    cc = np.concatenate(
        [np.array([remap[c] for c in cols[keep]], np.int64), np.full(n, len(RE_COLS))]
    )
    vv = np.concatenate([vals[keep], np.ones(n)])
    return dataclasses.replace(
        raw,
        shard_coo={
            "global": (rows[keepf], cols[keepf], vals[keepf]),
            "reShard": (rr, cc, vv),
        },
        shard_dims={
            "global": raw.shard_dims["global"],
            "reShard": len(RE_COLS) + 1,
        },
        id_tags={"groupId": ids},
    )


@pytest.fixture(scope="module")
def adult():
    train = _prep(read_libsvm(A9A, dim=124))
    test = _prep(read_libsvm(A9A + ".t", dim=124))
    return train, test


def _fit(train, with_re):
    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-8, max_iterations=100),
        regularization=RegularizationContext("L2"),
        reg_weight=1.0,
    )
    cfg_re = GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-8, max_iterations=60),
        regularization=RegularizationContext("L2"),
        reg_weight=5.0,
    )
    ccs = [CoordinateConfig(name="global", feature_shard="global", config=cfg)]
    if with_re:
        ccs.append(
            CoordinateConfig(
                name="per-group",
                feature_shard="reShard",
                config=cfg_re,
                random_effect_type="groupId",
            )
        )
    est = GameEstimator(
        task="logistic_regression",
        coordinate_configs=ccs,
        n_cd_iterations=2 if with_re else 1,
        dtype=jnp.float64,
    )
    return est.fit(train)[0].model


def _test_scores(model, test, with_re):
    rows, cols, vals = test.shard_coo["global"]
    x = np.zeros((test.n_rows, test.shard_dims["global"]))
    x[rows, cols] = vals
    means = np.asarray(model["global"].model.coefficients.means)
    s = x[:, : len(means)] @ means
    if with_re:
        re_m = model["per-group"]
        rr, cc, vv = test.shard_coo["reShard"]
        idx, val = _rows_to_ell(rr, cc, vv, test.n_rows)
        erow = jnp.asarray(re_m.rows_for(test.id_tags["groupId"]).astype(np.int32))
        s = s + np.asarray(
            re_m.score_ell_rows(erow, jnp.asarray(idx), jnp.asarray(val))
        )
    return s


def _test_auc(model, test, with_re):
    s = _test_scores(model, test, with_re)
    return float(area_under_roc_curve(jnp.asarray(s), jnp.asarray(test.labels)))


def test_entity_structure_is_real(adult):
    """The derived entities show the genuine skew of the underlying census
    data (not a uniform synthetic assignment)."""
    train, _ = adult
    _, cnt = np.unique(train.id_tags["groupId"], return_counts=True)
    assert len(cnt) > 80
    assert cnt.min() <= 5 and cnt.max() > 4000  # heavy real-world skew
    assert cnt.max() / np.median(cnt) > 50


def test_fixed_and_mixed_effect_thresholds(adult):
    """Held-out (a9a.t) AUC must not regress below the captured baselines,
    the random effects must recover the full captured lift (minus float
    noise), and the per-group MultiEvaluator view must hold up."""
    train, test = adult
    m_fixed = _fit(train, with_re=False)
    s_fixed = _test_scores(m_fixed, test, with_re=False)
    auc_fixed = float(
        area_under_roc_curve(jnp.asarray(s_fixed), jnp.asarray(test.labels))
    )
    assert auc_fixed > FIXED_AUC_CAPTURED - MARGIN, auc_fixed

    m_mixed = _fit(train, with_re=True)
    s_mixed = _test_scores(m_mixed, test, with_re=True)
    auc_mixed = float(
        area_under_roc_curve(jnp.asarray(s_mixed), jnp.asarray(test.labels))
    )
    assert auc_mixed > MIXED_AUC_CAPTURED - MARGIN, auc_mixed
    # the RE contribution is small but real on this dataset; require the
    # CAPTURED lift minus float noise — a partially-broken RE path that
    # recovers only a fraction of the lift must fail here
    assert auc_mixed - auc_fixed > CAPTURED_LIFT - LIFT_NOISE, (
        auc_fixed,
        auc_mixed,
        CAPTURED_LIFT,
    )

    # per-group thresholds via the MultiEvaluator grammar: AUC:groupId is
    # the unweighted mean of per-group AUCs (MultiEvaluator.scala semantics)
    suite = build_suite(
        ["AUC", "AUC:groupId"],
        test.labels,
        id_tags={"groupId": test.id_tags["groupId"]},
    )
    res_fixed = suite.evaluate(s_fixed).metrics
    res_mixed = suite.evaluate(s_mixed).metrics
    # self-check: the suite's pooled AUC must agree with the direct compute
    assert abs(res_mixed["AUC"] - auc_mixed) < 1e-12, (res_mixed, auc_mixed)
    grouped_fixed = res_fixed["AUC:groupId"]
    grouped_mixed = res_mixed["AUC:groupId"]
    assert grouped_mixed > GROUPED_AUC_FLOOR, (grouped_fixed, grouped_mixed)
    # per-group intercepts cancel inside each group, so any within-group
    # ranking change comes from the fitted age/capital deviations; they must
    # not DEGRADE per-group ranking beyond float-noise margin
    assert grouped_mixed > grouped_fixed - MARGIN, (grouped_fixed, grouped_mixed)
