"""Distributed fault tolerance: heartbeats, bounded-time collectives,
two-phase topology-aware checkpoints, and the kill-a-worker recovery drill.

The tier-1 tests here exercise the whole liveness surface in-process (fake
coordination clients, injected exchanges, the standard fault grammar); the
``slow``-marked drill at the bottom runs the REAL thing: two OS processes,
one killed mid-sweep by ``dist.collective:kill``, the survivor failing with
a typed timeout + a ``peer_lost`` flight dump within the budget, then both
relaunched with ``--resume`` to finish from the last committed two-phase
checkpoint and match an uninterrupted reference run."""

import glob
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.plan import PlanError, planner
from photon_ml_tpu.robust import distributed as rd
from photon_ml_tpu.robust import faults
from photon_ml_tpu.robust.checkpoint import (
    CheckpointIncompatibleError,
    CheckpointManager,
)
from photon_ml_tpu.robust.faults import InjectedIOError, SimulatedKill

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_state():
    yield
    faults.clear()
    rd.clear_collectives()


@pytest.fixture
def run():
    """Fresh telemetry scope so counter assertions see only this test."""
    r = obs.RunTelemetry()
    with obs.use_run(r):
        yield r


def counter_value(run, name, **labels):
    return run.registry.counter(name, "").labels(**labels).value


def _wait_until(predicate, timeout_s=10.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# ---------------------------------------------------------------- heartbeats


def test_heartbeat_write_read_roundtrip(tmp_path):
    d = str(tmp_path)
    rd.write_heartbeat(d, 0, 1)
    rd.write_heartbeat(d, 1, 7)
    recs = rd.read_heartbeats(d)
    assert set(recs) == {0, 1}
    assert recs[1]["seq"] == 7 and recs[1]["pid"] == os.getpid()
    # a torn record reads as a missing peer, not a crash
    with open(rd.heartbeat_path(d, 2), "w") as f:
        f.write('{"process": 2, "se')
    assert set(rd.read_heartbeats(d)) == {0, 1}


def test_heartbeat_ages_and_gauge(tmp_path, run):
    d = str(tmp_path)
    rd.write_heartbeat(d, 0, 1)
    ages = rd.heartbeat_ages(d, now=time.time() + 5.0)
    assert ages[0] == pytest.approx(5.0, abs=1.0)
    gauge = run.registry.gauge(
        "photon_dist_heartbeat_age_seconds", ""
    ).labels(process="0")
    assert gauge.value == pytest.approx(ages[0])


def test_stale_and_missing_peers_raise_typed_error(tmp_path):
    d = str(tmp_path)
    now = time.time()
    rd.write_heartbeat(d, 0, 1)
    rd.write_heartbeat(d, 1, 1)
    # fresh: no stale peers (self excluded either way)
    rd.check_peers(d, 2, stale_after_s=30.0, self_process=0, now=now)
    # peer 1's record ages past the budget; peer 2 never beat at all
    with pytest.raises(rd.PeerLostError, match=r"presumed lost"):
        rd.check_peers(d, 3, 5.0, self_process=0, now=now + 60.0)
    try:
        rd.check_peers(d, 3, 5.0, self_process=0, now=now + 60.0)
    except rd.PeerLostError as e:
        assert "p2=never" in str(e)
        assert "[1, 2]" in str(e)


def test_heartbeat_fault_site_fires(tmp_path, run):
    faults.configure("dist.heartbeat:io:1")
    with pytest.raises(InjectedIOError):
        rd.write_heartbeat(str(tmp_path), 0, 1)
    assert counter_value(
        run, "photon_faults_injected_total", site="dist.heartbeat", kind="io"
    ) == 1


def test_heartbeat_writer_beats_and_swallows_transient_io(tmp_path, run):
    d = str(tmp_path)
    w = rd.HeartbeatWriter(d, 0, interval_s=0.02).start()
    try:
        assert _wait_until(lambda: rd.read_heartbeats(d).get(0, {}).get("seq", 0) >= 3)
        # two transient write failures: swallowed + counted, then the next
        # beat repairs the record and seq keeps advancing
        faults.configure("dist.heartbeat:io:1x2")
        assert _wait_until(
            lambda: counter_value(
                run, "photon_swallowed_errors_total", site="dist.heartbeat"
            ) >= 2
        )
        seq_after_fault = rd.read_heartbeats(d)[0]["seq"]
        assert _wait_until(
            lambda: rd.read_heartbeats(d)[0]["seq"] > seq_after_fault
        )
    finally:
        w.stop()
    assert not w._thread.is_alive()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_heartbeat_kill_takes_down_the_writer_thread(tmp_path):
    """dist.heartbeat:kill is the starved-liveness-plane drill: the process
    keeps running but its beats stop, so peers see a growing age."""
    d = str(tmp_path)
    w = rd.HeartbeatWriter(d, 1, interval_s=0.02)
    w.start()  # beat 1 lands synchronously
    faults.configure("dist.heartbeat:kill:1")
    assert _wait_until(lambda: not w._thread.is_alive())
    seq_frozen = rd.read_heartbeats(d)[1]["seq"]
    time.sleep(0.1)
    assert rd.read_heartbeats(d)[1]["seq"] == seq_frozen


def test_heartbeat_writer_rejects_bad_interval(tmp_path):
    with pytest.raises(ValueError, match="interval must be > 0"):
        rd.HeartbeatWriter(str(tmp_path), 0, interval_s=0.0)


# ------------------------------------------------- bounded-time collectives


def test_sweep_barrier_fires_fault_site_once_per_sweep(run):
    faults.configure("dist.collective:kill:2")
    rd.sweep_barrier(0)  # sweep 1 survives
    with pytest.raises(SimulatedKill):
        rd.sweep_barrier(1)
    assert counter_value(
        run, "photon_faults_injected_total", site="dist.collective", kind="kill"
    ) == 1


def test_barrier_delay_fault_holds_the_process(run):
    faults.configure("dist.collective:delay80:1")
    t0 = time.perf_counter()
    rd.sweep_barrier(0)
    assert time.perf_counter() - t0 >= 0.06


def test_configure_collectives_arm_and_disarm():
    assert rd.collective_timeout() is None
    rd.configure_collectives(12.5, run_dir="/tmp/x", stale_after_s=3.0)
    assert rd.collective_timeout() == 12.5
    rd.configure_collectives(0)  # <= 0 disarms
    assert rd.collective_timeout() is None


class _FakeClient:
    """Stands in for jax's coordination-service client."""

    def __init__(self, error=None):
        self.error = error
        self.calls = []

    def wait_at_barrier(self, barrier_id, timeout_in_ms, process_ids=None):
        self.calls.append((barrier_id, timeout_in_ms))
        if self.error is not None:
            raise self.error


def _fake_two_process(monkeypatch, client):
    monkeypatch.setattr(rd, "_process_count", lambda: 2)
    monkeypatch.setattr(rd, "_coordination_client", lambda: client)


def test_barrier_ids_are_spmd_ordered_per_name(monkeypatch):
    client = _FakeClient()
    _fake_two_process(monkeypatch, client)
    rd.configure_collectives(5.0)
    rd.sweep_barrier(0)
    rd.sweep_barrier(1)
    rd.guard_collective("allgather_object")
    rd.sweep_barrier(1)  # same name again -> next sequence number
    assert [c[0] for c in client.calls] == [
        "photon:cd.sweep.0:1",
        "photon:cd.sweep.1:1",
        "photon:pre:allgather_object:1",
        "photon:cd.sweep.1:2",
    ]
    assert all(ms == 5000 for _, ms in client.calls)


def test_guard_collective_is_noop_unarmed(monkeypatch):
    client = _FakeClient()
    _fake_two_process(monkeypatch, client)
    rd.guard_collective("allgather_object")  # unarmed: no barrier issued
    assert client.calls == []


def test_barrier_deadline_translates_to_typed_timeout(
    monkeypatch, tmp_path, run
):
    d = str(tmp_path)
    rd.write_heartbeat(d, 0, 1)  # peer 1 never beats -> named in the error
    client = _FakeClient(
        error=RuntimeError("DEADLINE_EXCEEDED: barrier timed out")
    )
    _fake_two_process(monkeypatch, client)
    rd.configure_collectives(0.25, run_dir=d, stale_after_s=5.0)
    with pytest.raises(rd.DistributedTimeoutError) as ei:
        rd.sweep_barrier(3)
    msg = str(ei.value)
    assert "a peer process never arrived" in msg
    assert "budget 0.2s" in msg or "budget 0.3s" in msg
    assert "heartbeat-stale peers: [1]" in msg
    assert counter_value(
        run, "photon_dist_collective_timeouts_total", barrier="cd.sweep.3"
    ) == 1
    # and it is a DistributedError -> one except clause catches the family
    assert isinstance(ei.value, rd.DistributedError)


def test_barrier_peer_abort_also_translates(monkeypatch):
    """The coordination service can notice the dead peer BEFORE the deadline
    (missed service heartbeats) and abort the barrier — same typed error."""
    client = _FakeClient(
        error=RuntimeError("UNAVAILABLE: connection to peer task closed")
    )
    _fake_two_process(monkeypatch, client)
    rd.configure_collectives(5.0)
    with pytest.raises(rd.DistributedTimeoutError):
        rd.sweep_barrier(0)


def test_barrier_unrelated_error_is_not_translated(monkeypatch):
    client = _FakeClient(error=RuntimeError("PERMISSION_DENIED: bad token"))
    _fake_two_process(monkeypatch, client)
    rd.configure_collectives(5.0)
    with pytest.raises(RuntimeError, match="PERMISSION_DENIED"):
        rd.sweep_barrier(0)


def test_barrier_unarmed_multiprocess_is_blocking_shape(monkeypatch):
    # no budget armed: the barrier must NOT issue a client wait (collectives
    # keep their historical blocking behavior)
    client = _FakeClient()
    _fake_two_process(monkeypatch, client)
    rd.sweep_barrier(0)
    assert client.calls == []


# ------------------------------------- two-phase topology-aware checkpoints


class _State:
    """Minimal CDBoundaryState stand-in (mirrors tests/test_robust.py)."""

    def __init__(self, iteration=0, summed_scores=None):
        self.iteration = iteration
        self.coordinate_index = 0
        self.coordinate = "global"
        self.coordinate_order = ("global",)
        self.n_iterations = 3
        self.models = {"global": np.arange(3.0)}
        self.summed_scores = (
            np.ones(4) if summed_scores is None else summed_scores
        )
        self.best_eval = None
        self.best_models = {}
        self.evaluations = []
        self.trackers = {}
        self.train_losses = {}


class _FakeExchange:
    """Sequential stand-in for the allgather confirm exchange: the LAST
    caller (the coordinator, in these tests) sees every confirm."""

    def __init__(self):
        self.confirms = []

    def __call__(self, confirm):
        self.confirms.append(confirm)
        return list(self.confirms)

    def reset(self):
        self.confirms = []


_TOPOLOGY = {
    "mesh_axes": {"data": 8, "model": 1},
    "plan_fingerprint": "fp-aaaa",
}


def _pair(tmp_path, exchange):
    """Two managers simulating 2 processes over one shared directory."""
    mgrs = [
        CheckpointManager(
            str(tmp_path),
            fsync=False,
            process=i,
            n_processes=2,
            topology=dict(_TOPOLOGY),
            exchange=exchange,
        )
        for i in range(2)
    ]
    return mgrs[0], mgrs[1]


def _save_step(mgr0, mgr1, exchange, iteration):
    exchange.reset()
    # local row shards: p0 owns [it*10 .. it*10+4), p1 the next 4 rows
    base = float(iteration * 10)
    s1 = mgr1.save(_State(iteration, np.arange(4.0) + base + 4.0))
    s0 = mgr0.save(_State(iteration, np.arange(4.0) + base))
    assert s0 == s1  # sequence numbers agree across processes
    return s0


def test_two_phase_save_commits_shards_and_topology(tmp_path, run):
    ex = _FakeExchange()
    mgr0, mgr1 = _pair(tmp_path, ex)
    ckpt = _save_step(mgr0, mgr1, ex, iteration=0)
    names = sorted(os.listdir(ckpt))
    assert names == ["MANIFEST.json", "shard-p0.pkl", "shard-p1.pkl", "state.pkl"]
    with open(os.path.join(ckpt, "MANIFEST.json")) as f:
        manifest = json.load(f)
    assert [s["process"] for s in manifest["shards"]] == [0, 1]
    assert manifest["topology"] == {
        **_TOPOLOGY,
        "n_processes": 2,
        "global_rows": 8,
    }
    # restore re-concatenates the row shards in process order
    snap = CheckpointManager(str(tmp_path)).latest_valid()
    np.testing.assert_array_equal(snap.summed_scores, np.arange(8.0))
    assert counter_value(run, "photon_checkpoint_saves_total") == 1


def test_two_phase_torn_before_coordinator_phase_falls_back(tmp_path, run):
    """dist.commit tears process 0's phase-1 entry: peer 1's shard is on
    disk but no manifest ever lands — restore falls back one step."""
    ex = _FakeExchange()
    mgr0, mgr1 = _pair(tmp_path, ex)
    _save_step(mgr0, mgr1, ex, iteration=0)  # the consistent step
    ex.reset()
    faults.configure("dist.commit:io:2")
    mgr1.save(_State(1, np.arange(4.0) + 14.0))  # call 1: p1's shard lands
    with pytest.raises(InjectedIOError):
        mgr0.save(_State(1, np.arange(4.0) + 10.0))  # call 2: p0 dies
    torn = os.path.join(str(tmp_path), "ckpt-000001")
    assert os.path.exists(os.path.join(torn, "shard-p1.pkl"))
    assert not os.path.exists(os.path.join(torn, "MANIFEST.json"))
    snap = CheckpointManager(str(tmp_path)).latest_valid()
    assert snap.iteration == 0
    np.testing.assert_array_equal(snap.summed_scores, np.arange(8.0))
    assert counter_value(
        run, "photon_checkpoint_skipped_total", reason="corrupt"
    ) == 1


def test_two_phase_killed_at_commit_point_falls_back(tmp_path, run):
    """dist.commit kills the coordinator AFTER shards + payload are durable
    but before the manifest — the torn save must read as 'no checkpoint',
    exactly like a corrupt single-process one."""
    ex = _FakeExchange()
    mgr0, mgr1 = _pair(tmp_path, ex)
    _save_step(mgr0, mgr1, ex, iteration=0)
    ex.reset()
    faults.configure("dist.commit:kill:3")  # p1 phase-1, p0 phase-1, COMMIT
    mgr1.save(_State(1, np.arange(4.0) + 14.0))
    with pytest.raises(SimulatedKill):
        mgr0.save(_State(1, np.arange(4.0) + 10.0))
    torn = os.path.join(str(tmp_path), "ckpt-000001")
    assert os.path.exists(os.path.join(torn, "state.pkl"))
    assert os.path.exists(os.path.join(torn, "shard-p0.pkl"))
    assert not os.path.exists(os.path.join(torn, "MANIFEST.json"))
    snap = CheckpointManager(str(tmp_path)).latest_valid()
    assert snap.iteration == 0


def test_two_phase_corrupt_shard_digest_falls_back(tmp_path, run):
    ex = _FakeExchange()
    mgr0, mgr1 = _pair(tmp_path, ex)
    _save_step(mgr0, mgr1, ex, iteration=0)
    ckpt1 = _save_step(mgr0, mgr1, ex, iteration=1)
    # flip bytes inside the newest step's p1 shard: the manifest exists and
    # the payload digest passes, but the SHARD digest must catch it
    shard = os.path.join(ckpt1, "shard-p1.pkl")
    blob = bytearray(open(shard, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(bytes(blob))
    snap = CheckpointManager(str(tmp_path)).latest_valid()
    assert snap.iteration == 0
    assert counter_value(
        run, "photon_checkpoint_skipped_total", reason="corrupt"
    ) == 1


def test_restore_topology_same_reshape_and_refusals(tmp_path, run):
    ex = _FakeExchange()
    mgr0, mgr1 = _pair(tmp_path, ex)
    _save_step(mgr0, mgr1, ex, iteration=0)
    reader = CheckpointManager(str(tmp_path))
    same = {**_TOPOLOGY, "n_processes": 2, "global_rows": 8}
    assert reader.latest_valid(expect_topology=same).iteration == 0
    # LEGAL reshape: process count changed, padded row totals agree — the
    # shards re-concatenate into the same global row order
    reshaped = {**_TOPOLOGY, "n_processes": 1, "global_rows": 8}
    snap = reader.latest_valid(expect_topology=reshaped)
    np.testing.assert_array_equal(snap.summed_scores, np.arange(8.0))
    # UNSOUND reshape: row totals disagree -> ledger-pinned refusal
    with pytest.raises(
        CheckpointIncompatibleError,
        match="the process count changed and no legal reshape exists",
    ):
        reader.latest_valid(
            expect_topology={**_TOPOLOGY, "n_processes": 4, "global_rows": 12}
        )
    # model-axis reshape -> refusal
    with pytest.raises(
        CheckpointIncompatibleError,
        match="mesh reshape across the model axis is not supported",
    ):
        reader.latest_valid(
            expect_topology={
                "mesh_axes": {"data": 4, "model": 2},
                "plan_fingerprint": "fp-aaaa",
                "n_processes": 2,
                "global_rows": 8,
            }
        )
    # changed execution plan -> refusal
    with pytest.raises(
        CheckpointIncompatibleError,
        match="changed execution plan is not supported",
    ):
        reader.latest_valid(
            expect_topology={**_TOPOLOGY,
                             "plan_fingerprint": "fp-bbbb",
                             "n_processes": 2, "global_rows": 8}
        )


def test_manager_validates_process_arguments(tmp_path):
    with pytest.raises(ValueError, match="n_processes must be >= 1"):
        CheckpointManager(str(tmp_path), n_processes=0)
    with pytest.raises(ValueError, match="process must be in"):
        CheckpointManager(str(tmp_path), process=2, n_processes=2)


# --------------------------------------------- planner topology unit checks


def test_check_checkpoint_topology_missing_keys_skip():
    # manifests that predate the protocol restore as before
    planner.check_checkpoint_topology({}, {"n_processes": 4, "global_rows": 9})
    planner.check_checkpoint_topology({"n_processes": 2}, {})
    # same process count: row totals are not even consulted
    planner.check_checkpoint_topology(
        {"n_processes": 2, "global_rows": 8},
        {"n_processes": 2, "global_rows": 10},
    )


def test_check_checkpoint_topology_legal_reshape():
    planner.check_checkpoint_topology(
        {"n_processes": 2, "global_rows": 8, "mesh_axes": {"data": 8}},
        {"n_processes": 4, "global_rows": 8, "mesh_axes": {"data": 8}},
    )


def test_check_checkpoint_topology_refuses_row_mismatch():
    with pytest.raises(
        PlanError,
        match="the process count changed and no legal reshape exists",
    ):
        planner.check_checkpoint_topology(
            {"n_processes": 2, "global_rows": 8},
            {"n_processes": 3, "global_rows": 9},
        )


def test_plan_fingerprint_is_topology_independent():
    """The fingerprint pins WHAT the model is (coordinates, layouts,
    dtypes, residency), never WHERE it runs — a legal mesh/process reshape
    keeps it, a changed coordinate configuration does not."""
    import dataclasses as dc

    @dc.dataclass
    class _Reg:
        reg_type: str = "L2"

    @dc.dataclass
    class _Cfg:
        variance_type: str = "NONE"
        down_sampling_rate: float = 1.0
        regularization: _Reg = dc.field(default_factory=_Reg)

    @dc.dataclass
    class _CC:
        name: str = "c0"
        feature_shard: str = "global"
        layout: str = "auto"
        feature_dtype: object = None
        hbm_budget_mb: object = None
        is_random_effect: bool = False
        config: _Cfg = dc.field(default_factory=_Cfg)
        normalization: object = None
        regularize_by_prior: bool = False

    def _fp(layout="auto", mesh=None, n_processes=1):
        plan = planner.resolve(
            [_CC(layout=layout)],
            mesh=mesh,
            n_processes=n_processes,
            distributed=n_processes > 1,
        )
        return planner.plan_fingerprint(plan)

    fp = _fp()
    assert fp == _fp() and len(fp) == 16  # stable digest
    # topology-independent: mesh and process count do not move it
    assert _fp(mesh={"data": 8, "model": 1}, n_processes=2) == fp
    # model-identity-dependent: a changed layout does
    assert _fp(layout="dense") != fp


# ------------------------------------------------------ CLI resume refusal


def _write_logistic_avro(tmp_path, n=64, d=4, seed=5):
    from photon_ml_tpu.io import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w)))).astype(int)
    recs = [
        {
            "label": float(y[i]),
            "features": [
                {"name": f"f{j}", "term": "", "value": float(x[i, j])}
                for j in range(d)
            ],
        }
        for i in range(n)
    ]
    p = str(tmp_path / "train.avro")
    write_avro_file(p, TRAINING_EXAMPLE_AVRO, recs)
    return p


def test_cli_resume_refuses_unsound_process_count_change(tmp_path):
    """Satellite: ``train --resume`` against a checkpoint stamped with a
    different process count and disagreeing padded row totals must refuse
    with the typed topology error, not crash mid-sweep."""
    from photon_ml_tpu.cli import train

    data = _write_logistic_avro(tmp_path)
    ckpt = str(tmp_path / "ckpt")
    common = [
        "--input-data", data,
        "--task", "logistic_regression",
        "--feature-shard", "name=global,bags=features",
        "--coordinate",
        "name=global,shard=global,optimizer=LBFGS,tolerance=1e-8,"
        "max.iter=30,reg.type=L2,reg.weights=1",
        "--coordinate-descent-iterations", "2",
        "--checkpoint-dir", ckpt,
        "--checkpoint-every", "1",
    ]
    train.run(common + ["--output-dir", str(tmp_path / "out1")])
    manifests = sorted(
        glob.glob(os.path.join(ckpt, "cd-boundaries", "ckpt-*", "MANIFEST.json"))
    )
    assert manifests, "checkpointed run left no boundary manifests"
    # forge the newest manifest's topology: written by a 2-process run whose
    # padded global row total disagrees with this (single-process) resume
    with open(manifests[-1]) as f:
        manifest = json.load(f)
    assert manifest["topology"]["n_processes"] == 1
    manifest["topology"]["n_processes"] = 2
    manifest["topology"]["global_rows"] = 999_999
    with open(manifests[-1], "w") as f:
        json.dump(manifest, f)
    with pytest.raises(
        CheckpointIncompatibleError,
        match="the process count changed and no legal reshape exists",
    ):
        train.run(
            common + ["--resume", "--output-dir", str(tmp_path / "out2")]
        )


def test_cli_distributed_flags_parse():
    from photon_ml_tpu.cli.train import build_parser

    args = build_parser().parse_args(
        [
            "--input-data", "in", "--output-dir", "out",
            "--collective-timeout", "30",
            "--heartbeat-interval", "0.5",
            "--heartbeat-timeout", "7",
        ]
    )
    assert args.collective_timeout == 30.0
    assert args.heartbeat_interval == 0.5
    assert args.heartbeat_timeout == 7.0
    defaults = build_parser().parse_args(["--input-data", "i", "--output-dir", "o"])
    assert defaults.collective_timeout == 60.0
    assert defaults.heartbeat_interval == 1.0
    assert defaults.heartbeat_timeout == 10.0


# ------------------------------------------------- the kill-a-worker drill


_DRILL_WORKER = """
import os
import sys
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:
    pass  # jax 0.4.x: XLA_FLAGS in the env pins the 4 virtual devices
try:
    # cross-host collectives on the CPU backend need an explicit impl on
    # jax versions that don't default it
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.config.update("jax_enable_x64", True)

from photon_ml_tpu.cli import train

try:
    train.run(sys.argv[1:])
    print("WORKER_OK", jax.process_index())
    sys.stdout.flush()
except BaseException as e:  # noqa: BLE001 - drill: report + hard-exit
    import traceback
    traceback.print_exc()
    print("WORKER_DIED %s: %s" % (type(e).__name__, e), file=sys.stderr)
    sys.stderr.flush()
    # hard exit: with a dead peer the graceful jax shutdown barrier would
    # block for its own timeout — the drill wants bounded-time death
    os._exit(70)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _drill_round(tmp_path, data, index_dir, ckpt, out, metrics_prefix,
                 extra=(), env_by_proc=None, timeout=420):
    env_base = {**os.environ, "PYTHONPATH": REPO}
    # 4 virtual CPU devices per process (jax 0.4.x spells this via XLA_FLAGS)
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env_base.pop("PHOTON_FAULTS", None)
    port = _free_port()
    procs = []
    for i in range(2):
        env = dict(env_base)
        env.update((env_by_proc or {}).get(i, {}))
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-c", _DRILL_WORKER,
                    "--input-data", data,
                    "--feature-shard", "name=global,bags=features",
                    "--task", "logistic_regression",
                    "--coordinate",
                    "name=global,shard=global,optimizer=LBFGS,tolerance=1e-13,"
                    "max.iter=400,reg.type=L2,reg.weights=1",
                    "--coordinate-descent-iterations", "3",
                    "--feature-index-dir", index_dir,
                    "--checkpoint-dir", ckpt,
                    "--checkpoint-every", "1",
                    "--collective-timeout", "20",
                    "--heartbeat-interval", "0.5",
                    "--heartbeat-timeout", "6",
                    "--metrics-out", str(tmp_path / f"{metrics_prefix}-p{i}"),
                    "--output-dir", out,
                    "--mesh-shape", "data=8",
                    "--distributed",
                    f"coordinator=localhost:{port},process={i},n=2",
                    *extra,
                ],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out_s, err_s = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"drill round ({metrics_prefix}) timed out — "
                        "the liveness layer failed to bound the hang")
        outs.append((p.returncode, out_s, err_s))
    return outs


@pytest.mark.slow
def test_kill_a_worker_drill(tmp_path):
    """THE recovery drill (tentpole acceptance): worker 1 dies at its second
    sweep boundary; worker 0 fails with a typed DistributedTimeoutError
    within the collective budget and dumps a peer_lost postmortem; both
    relaunch with --resume from the last committed two-phase checkpoint and
    finish with the same model as an uninterrupted run."""
    from photon_ml_tpu.cli import index as index_cli
    from photon_ml_tpu.io.index_map import load_partitioned
    from photon_ml_tpu.io.model_io import load_game_model

    data = _write_logistic_avro(tmp_path, n=320, d=6, seed=7)
    index_dir = str(tmp_path / "index")
    index_cli.run(
        ["--input-data", data, "--feature-shard", "name=global,bags=features",
         "--output-dir", index_dir]
    )

    # uninterrupted 3-sweep reference
    out_ref = str(tmp_path / "out-ref")
    outs = _drill_round(
        tmp_path, data, index_dir, str(tmp_path / "ckpt-ref"), out_ref, "ref"
    )
    for rc, out_s, err_s in outs:
        assert rc == 0, f"reference worker failed:\n{out_s}\n{err_s}"
        assert "WORKER_OK" in out_s

    # faulted round: worker 1 killed at sweep boundary 2
    ckpt = str(tmp_path / "ckpt-drill")
    out_drill = str(tmp_path / "out-drill")
    t0 = time.monotonic()
    outs = _drill_round(
        tmp_path, data, index_dir, ckpt, out_drill, "drill",
        env_by_proc={1: {"PHOTON_FAULTS": "dist.collective:kill:2"}},
        timeout=300,
    )
    wall = time.monotonic() - t0
    (rc0, out0, err0), (rc1, out1, err1) = outs
    assert rc1 == 70 and "WORKER_DIED SimulatedKill" in err1, (out1, err1)
    # the survivor's failure is TYPED and BOUNDED, not a hang
    assert rc0 == 70, (out0, err0)
    assert "WORKER_DIED DistributedTimeoutError" in err0, err0
    assert "a peer process never arrived" in err0, err0
    assert wall < 240, f"detection not bounded: {wall:.0f}s"
    # the survivor (coordinator) dumped the peer_lost postmortem
    dumps = glob.glob(
        os.path.join(str(tmp_path / "drill-p0"), "flight", "flight-peer_lost-*.json")
    )
    assert dumps, "no peer_lost flight-recorder dump on the survivor"
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["trigger"]["kind"] == "peer_lost"
    # a committed two-phase boundary checkpoint exists to resume from
    manifests = glob.glob(
        os.path.join(ckpt, "cd-boundaries", "ckpt-*", "MANIFEST.json")
    )
    assert manifests, "no committed checkpoint before the kill"
    with open(sorted(manifests)[-1]) as f:
        manifest = json.load(f)
    assert manifest["topology"]["n_processes"] == 2
    assert len(manifest["shards"]) == 2

    # recovery: relaunch BOTH processes with --resume; the run finishes the
    # remaining sweep from the committed checkpoint
    outs = _drill_round(
        tmp_path, data, index_dir, ckpt, out_drill, "resume",
        extra=("--resume",),
    )
    for rc, out_s, err_s in outs:
        assert rc == 0, f"resume worker failed:\n{out_s}\n{err_s}"
        assert "WORKER_OK" in out_s
    assert any(
        "resuming from checkpoint" in err_s for _, _, err_s in outs
    ), "resume round did not actually restore a checkpoint"

    # parity: resumed final model vs the uninterrupted reference (x64,
    # tightly converged LBFGS: agreement is at solver-noise scale)
    imaps = {"global": load_partitioned(index_dir, "global")}
    w_resumed = np.asarray(
        load_game_model(
            os.path.join(out_drill, "models", "best"), imaps,
            task="logistic_regression",
        ).models["global"].model.coefficients.means
    )
    w_ref = np.asarray(
        load_game_model(
            os.path.join(out_ref, "models", "best"), imaps,
            task="logistic_regression",
        ).models["global"].model.coefficients.means
    )
    np.testing.assert_allclose(w_resumed, w_ref, rtol=1e-9, atol=1e-9)
