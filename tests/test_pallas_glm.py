"""Parity tests for the fused Pallas GLM kernels (ops/pallas_glm.py).

Strategy: the kernels must be bit-for-bit interchangeable (to f32 tolerance)
with the two-pass jnp path on the SAME padded batch — every loss, with and
without normalization, weights/offsets, L2 and prior-centered regularization.
On CPU they run under interpret=True; the compiled TPU path shares every line
except the Mosaic lowering.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.problem import GLMOptimizationConfig, GLMProblem, _fusion_mode
from photon_ml_tpu.ops import pallas_glm
from photon_ml_tpu.ops.features import batch_from_dense, pad_batch
from photon_ml_tpu.ops.glm import GLMObjective, compute_variances
from photon_ml_tpu.ops.losses import LOSSES
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig


D = 256
TN = pallas_glm.tile_rows(D)


def _make_batch(rng, n, d=D, dtype=np.float32):
    x = rng.standard_normal((n, d)).astype(dtype)
    y = (rng.random(n) > 0.5).astype(dtype)
    off = (rng.standard_normal(n) * 0.1).astype(dtype)
    wt = (rng.random(n) + 0.5).astype(dtype)
    return batch_from_dense(x, y, offsets=off, weights=wt, dtype=jnp.dtype(dtype))


def _norm_ctx(rng, d=D, dtype=np.float32):
    return NormalizationContext(
        factors=jnp.asarray((rng.random(d) + 0.5).astype(dtype)),
        shifts=jnp.asarray(((rng.random(d) - 0.5) * 0.2).astype(dtype)),
        intercept_index=0,
    )


@pytest.mark.parametrize("loss_name", sorted(LOSSES))
@pytest.mark.parametrize("with_norm", [False, True])
def test_fused_value_grad_and_hv_parity(rng, loss_name, with_norm):
    loss = LOSSES[loss_name]
    batch = _make_batch(rng, 2 * TN)
    if loss_name in ("poisson",):
        batch = dataclasses.replace(batch, labels=jnp.abs(batch.labels) * 2)
    norm = _norm_ctx(rng) if with_norm else None
    pm = jnp.asarray((rng.standard_normal(D) * 0.01).astype(np.float32))
    pp = jnp.asarray((rng.random(D) + 0.5).astype(np.float32))
    base = GLMObjective(
        loss=loss, batch=batch, l2=0.3, norm=norm, prior_mean=pm, prior_precision=pp
    )
    fused = dataclasses.replace(base, fused="interpret")
    w = jnp.asarray((rng.standard_normal(D) * 0.1).astype(np.float32))
    v = jnp.asarray(rng.standard_normal(D).astype(np.float32))

    v0, g0 = base.value_and_grad(w)
    v1, g1 = fused.value_and_grad(w)
    np.testing.assert_allclose(float(v1), float(v0), rtol=2e-6)
    # f32 accumulation order differs (per-tile partial sums vs one reduce), so
    # compare against the result's own magnitude, not element-wise rtol
    g0, g1 = np.asarray(g0), np.asarray(g1)
    assert np.max(np.abs(g1 - g0)) <= 3e-5 * max(np.max(np.abs(g0)), 1.0)

    h0 = np.asarray(base.hessian_vector(w, v))
    h1 = np.asarray(fused.hessian_vector(w, v))
    assert np.max(np.abs(h1 - h0)) <= 3e-5 * max(np.max(np.abs(h0)), 1.0)

    d0 = np.asarray(base.hessian_diagonal(w))
    d1 = np.asarray(fused.hessian_diagonal(w))
    assert np.max(np.abs(d1 - d0)) <= 3e-5 * max(np.max(np.abs(d0)), 1.0)


def test_fused_under_jit_and_partial_tile(rng):
    """The fused objective must jit (solvers trace it), handle a row count
    that is NOT a tile multiple (in-kernel masking of the last tile), and
    ignore explicit weight-0 padding rows exactly like the jnp path does."""
    batch = _make_batch(rng, TN + 7)  # deliberately not a tile multiple
    base = GLMObjective(loss=LOSSES["logistic"], batch=batch, l2=0.1)
    fused = dataclasses.replace(base, fused="interpret")
    fused_padded = GLMObjective(
        loss=LOSSES["logistic"],
        batch=pad_batch(batch, 2 * TN),
        l2=0.1,
        fused="interpret",
    )
    w = jnp.asarray((rng.standard_normal(D) * 0.1).astype(np.float32))

    from photon_ml_tpu.ops.glm import vg_fn

    @jax.jit
    def run(f, w):
        return f(w)

    v0, g0 = run(vg_fn(base), w)
    for obj in (fused, fused_padded):
        v1, g1 = run(vg_fn(obj), w)
        np.testing.assert_allclose(float(v1), float(v0), rtol=2e-6)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-4, atol=1e-4)


def test_fusion_mode_gating(rng, monkeypatch):
    """_fusion_mode: off by default on CPU (auto), on under interpret, and
    never for sparse layouts, tiny batches, or misaligned feature dims."""
    ok = _make_batch(rng, pallas_glm.MIN_FUSED_ROWS)
    monkeypatch.setenv("PHOTON_PALLAS", "auto")
    assert _fusion_mode(ok) == (None, None)  # CPU backend
    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    assert _fusion_mode(ok) == ("interpret", None)
    # too few rows
    assert _fusion_mode(_make_batch(rng, 512)) == (None, None)
    # misaligned feature dim
    assert _fusion_mode(_make_batch(rng, pallas_glm.MIN_FUSED_ROWS, d=200)) == (None, None)
    # f64 batch (x64 test mode)
    assert _fusion_mode(
        _make_batch(rng, pallas_glm.MIN_FUSED_ROWS, dtype=np.float64)
    ) == (None, None)
    monkeypatch.setenv("PHOTON_PALLAS", "off")
    assert _fusion_mode(ok) == (None, None)
    monkeypatch.setenv("PHOTON_PALLAS", "bogus")
    with pytest.raises(ValueError):
        _fusion_mode(ok)


def test_fusion_mode_sharded_batches(rng, monkeypatch):
    """A DATA-axis-sharded dense batch fuses via shard_map (mesh returned);
    model-axis feature sharding falls back to the jnp path."""
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.mesh import shard_batch

    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    batch = _make_batch(rng, pallas_glm.MIN_FUSED_ROWS)
    mesh = make_mesh(n_data=4, n_model=2)
    sharded = shard_batch(batch, mesh)
    mode, fmesh = _fusion_mode(sharded)
    assert mode == "interpret" and fmesh is mesh

    sharded_model = shard_batch(batch, mesh, shard_features_dim=True)
    assert _fusion_mode(sharded_model) == (None, None)


def test_sharded_fused_matches_unsharded(rng, monkeypatch):
    """shard_map'd fused kernels on an 8-device data-parallel mesh produce
    the same objective value/grad/Hv as the single-device jnp path."""
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.mesh import shard_batch

    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    n = pallas_glm.MIN_FUSED_ROWS + 13  # force partial tiles per shard
    batch = _make_batch(rng, n)
    mesh = make_mesh(n_data=8, n_model=1)
    sharded = shard_batch(batch, mesh)  # zero-weight-pads rows to the mesh
    mode, fmesh = _fusion_mode(sharded)
    assert mode == "interpret" and fmesh is mesh

    base = GLMObjective(loss=LOSSES["logistic"], batch=batch, l2=0.2)
    fused = GLMObjective(
        loss=LOSSES["logistic"], batch=sharded, l2=0.2,
        fused="interpret", fused_mesh=mesh,
    )
    w = jnp.asarray((rng.standard_normal(D) * 0.1).astype(np.float32))
    v = jnp.asarray(rng.standard_normal(D).astype(np.float32))

    v0, g0 = base.value_and_grad(w)
    v1, g1 = fused.value_and_grad(w)
    np.testing.assert_allclose(float(v1), float(v0), rtol=2e-6)
    g0, g1 = np.asarray(g0), np.asarray(g1)
    assert np.max(np.abs(g1 - g0)) <= 3e-5 * max(np.max(np.abs(g0)), 1.0)

    h0 = np.asarray(base.hessian_vector(w, v))
    h1 = np.asarray(fused.hessian_vector(w, v))
    assert np.max(np.abs(h1 - h0)) <= 3e-5 * max(np.max(np.abs(h0)), 1.0)

    d0 = np.asarray(base.hessian_diagonal(w))
    d1 = np.asarray(fused.hessian_diagonal(w))
    assert np.max(np.abs(d1 - d0)) <= 3e-5 * max(np.max(np.abs(d0)), 1.0)

    # the shifts path of the sharded stats kernel (s1/s0 psums)
    norm = _norm_ctx(rng)
    base_n = dataclasses.replace(base, norm=norm)
    fused_n = dataclasses.replace(fused, norm=norm)
    dn0 = np.asarray(base_n.hessian_diagonal(w))
    dn1 = np.asarray(fused_n.hessian_diagonal(w))
    assert np.max(np.abs(dn1 - dn0)) <= 3e-5 * max(np.max(np.abs(dn0)), 1.0)


@pytest.mark.parametrize("d", [128, 384, 1024])
@pytest.mark.parametrize("n_off", [0, 1, 127])
def test_kernel_shape_sweep(rng, d, n_off):
    """Property sweep over feature dims and row remainders (full tiles,
    off-by-one, near-full partial tile): fused value+grad must match the jnp
    path at every shape the gating can admit."""
    n = pallas_glm.tile_rows(d) * 2 + n_off
    x = (rng.standard_normal((n, d)) * 0.4).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    batch = batch_from_dense(x, y)
    base = GLMObjective(loss=LOSSES["logistic"], batch=batch, l2=0.1)
    fused = dataclasses.replace(base, fused="interpret")
    w = jnp.asarray((rng.standard_normal(d) * 0.1).astype(np.float32))
    v0, g0 = base.value_and_grad(w)
    v1, g1 = fused.value_and_grad(w)
    np.testing.assert_allclose(float(v1), float(v0), rtol=2e-6)
    g0, g1 = np.asarray(g0), np.asarray(g1)
    assert np.max(np.abs(g1 - g0)) <= 3e-5 * max(np.max(np.abs(g0)), 1.0)


def test_end_to_end_sharded_solve(rng, monkeypatch):
    """GLMProblem.run on a mesh-sharded batch picks the shard_map fused path
    and converges to the same model as the unsharded unfused solve."""
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.mesh import shard_batch

    n = pallas_glm.MIN_FUSED_ROWS
    batch = _make_batch(rng, n)
    problem = GLMProblem(
        task="logistic_regression",
        config=GLMOptimizationConfig(
            optimizer=OptimizerConfig(tolerance=1e-9, max_iterations=60),
            regularization=RegularizationContext("L2"),
            reg_weight=1.0,
        ),
    )
    monkeypatch.setenv("PHOTON_PALLAS", "off")
    m0, r0 = problem.run(batch)
    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    mesh = make_mesh(n_data=8, n_model=1)
    m1, r1 = problem.run(shard_batch(batch, mesh))
    # two f32 solvers with different reduction orders walk different
    # trajectories at an unreachably tight tolerance; assert they reach the
    # same optimum: objective values agree tightly, coefficients to scale
    np.testing.assert_allclose(float(r1.loss), float(r0.loss), rtol=1e-5)
    w0_, w1_ = np.asarray(m0.coefficients.means), np.asarray(m1.coefficients.means)
    assert np.max(np.abs(w1_ - w0_)) <= 5e-3 * max(np.max(np.abs(w0_)), 1.0)


@pytest.mark.parametrize("optimizer", ["LBFGS", "TRON"])
def test_end_to_end_solve_matches_unfused(rng, monkeypatch, optimizer):
    """GLMProblem.run with PHOTON_PALLAS=interpret converges to the same model
    as the jnp path — the full solver loop (L-BFGS line search / TRON CG)
    driving the fused kernels."""
    n = pallas_glm.MIN_FUSED_ROWS
    batch = _make_batch(rng, n)
    problem = GLMProblem(
        task="logistic_regression",
        config=GLMOptimizationConfig(
            optimizer=OptimizerConfig(
                optimizer_type=optimizer, tolerance=1e-9, max_iterations=60
            ),
            regularization=RegularizationContext("L2"),
            reg_weight=1.0,
            variance_type="SIMPLE",
        ),
    )
    monkeypatch.setenv("PHOTON_PALLAS", "off")
    m0, r0 = problem.run(batch)
    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    m1, r1 = problem.run(batch)
    np.testing.assert_allclose(
        np.asarray(m1.coefficients.means),
        np.asarray(m0.coefficients.means),
        rtol=1e-3,
        atol=1e-5,
    )
    # variances come from the (unfused) hessian_diagonal on the padded batch;
    # weight-0 padding rows must not change them
    np.testing.assert_allclose(
        np.asarray(m1.coefficients.variances),
        np.asarray(m0.coefficients.variances),
        rtol=1e-3,
        atol=1e-6,
    )


def test_tile_rows_and_eligibility_constants():
    """Guards the VMEM-derived tiling rules: budgets per dtype, the parts
    divisor for multi-temporary kernels, the [128, 2048] clamp, and the
    lane-dim multiple-of-128 invariant (Mosaic requirement on the [1, tn]
    blocks — see the measured OOM notes in ops/pallas_glm.py)."""
    # f32 budget 2MB: d=1024 -> 512 rows; bf16 budget 4MB: d=1024 -> 2048
    assert pallas_glm.tile_rows(1024, 4) == 512
    assert pallas_glm.tile_rows(1024, 2) == 2048
    # parts=2 halves the budget (the Hessian-stats kernel's x*x temporary)
    assert pallas_glm.tile_rows(1024, 4, parts=2) == 256
    # clamps: tiny d caps at 2048 rows; the max fused dims keep >= 128 rows
    assert pallas_glm.tile_rows(128, 4) == 2048
    assert pallas_glm.tile_rows(pallas_glm.MAX_FUSED_DIM_F32, 4) == 128
    assert pallas_glm.tile_rows(pallas_glm.MAX_FUSED_DIM_BF16, 2) == 256
    for d in (128, 384, 1024, 4096, 8192):
        for itemsize in (2, 4):
            for parts in (1, 2):
                tn = pallas_glm.tile_rows(d, itemsize, parts)
                assert tn % 128 == 0 and 128 <= tn <= 2048

    import jax.numpy as jnp

    n = pallas_glm.MIN_FUSED_ROWS
    # dtype-specific dim ceilings
    assert pallas_glm.eligible(n, pallas_glm.MAX_FUSED_DIM_F32, jnp.float32)
    assert not pallas_glm.eligible(n, pallas_glm.MAX_FUSED_DIM_F32 + 128, jnp.float32)
    assert pallas_glm.eligible(n, pallas_glm.MAX_FUSED_DIM_BF16, jnp.bfloat16)
    assert not pallas_glm.eligible(n, pallas_glm.MAX_FUSED_DIM_BF16 + 128, jnp.bfloat16)
    assert not pallas_glm.eligible(n, 1024, jnp.float64)
