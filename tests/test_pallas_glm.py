"""Parity tests for the fused Pallas GLM kernels (ops/pallas_glm.py).

Strategy: the kernels must be bit-for-bit interchangeable (to f32 tolerance)
with the two-pass jnp path on the SAME padded batch — every loss, with and
without normalization, weights/offsets, L2 and prior-centered regularization.
On CPU they run under interpret=True; the compiled TPU path shares every line
except the Mosaic lowering.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.problem import GLMOptimizationConfig, GLMProblem, _fusion_mode
from photon_ml_tpu.ops import pallas_glm
from photon_ml_tpu.ops.features import batch_from_dense, pad_batch
from photon_ml_tpu.ops.glm import GLMObjective, compute_variances
from photon_ml_tpu.ops.losses import LOSSES
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig


D = 256
TN = pallas_glm.tile_rows(D)


def _make_batch(rng, n, d=D, dtype=np.float32):
    x = rng.standard_normal((n, d)).astype(dtype)
    y = (rng.random(n) > 0.5).astype(dtype)
    off = (rng.standard_normal(n) * 0.1).astype(dtype)
    wt = (rng.random(n) + 0.5).astype(dtype)
    return batch_from_dense(x, y, offsets=off, weights=wt, dtype=jnp.dtype(dtype))


def _norm_ctx(rng, d=D, dtype=np.float32):
    return NormalizationContext(
        factors=jnp.asarray((rng.random(d) + 0.5).astype(dtype)),
        shifts=jnp.asarray(((rng.random(d) - 0.5) * 0.2).astype(dtype)),
        intercept_index=0,
    )


@pytest.mark.parametrize("loss_name", sorted(LOSSES))
@pytest.mark.parametrize("with_norm", [False, True])
def test_fused_value_grad_and_hv_parity(rng, loss_name, with_norm):
    loss = LOSSES[loss_name]
    batch = _make_batch(rng, 2 * TN)
    if loss_name in ("poisson",):
        batch = dataclasses.replace(batch, labels=jnp.abs(batch.labels) * 2)
    norm = _norm_ctx(rng) if with_norm else None
    pm = jnp.asarray((rng.standard_normal(D) * 0.01).astype(np.float32))
    pp = jnp.asarray((rng.random(D) + 0.5).astype(np.float32))
    base = GLMObjective(
        loss=loss, batch=batch, l2=0.3, norm=norm, prior_mean=pm, prior_precision=pp
    )
    fused = dataclasses.replace(base, fused="interpret")
    w = jnp.asarray((rng.standard_normal(D) * 0.1).astype(np.float32))
    v = jnp.asarray(rng.standard_normal(D).astype(np.float32))

    v0, g0 = base.value_and_grad(w)
    v1, g1 = fused.value_and_grad(w)
    np.testing.assert_allclose(float(v1), float(v0), rtol=2e-6)
    # f32 accumulation order differs (per-tile partial sums vs one reduce), so
    # compare against the result's own magnitude, not element-wise rtol
    g0, g1 = np.asarray(g0), np.asarray(g1)
    assert np.max(np.abs(g1 - g0)) <= 3e-5 * max(np.max(np.abs(g0)), 1.0)

    h0 = np.asarray(base.hessian_vector(w, v))
    h1 = np.asarray(fused.hessian_vector(w, v))
    assert np.max(np.abs(h1 - h0)) <= 3e-5 * max(np.max(np.abs(h0)), 1.0)


def test_fused_under_jit_and_row_padding(rng):
    """The fused objective must jit (solvers trace it) and ignore weight-0
    padding rows exactly like the jnp path does."""
    batch = _make_batch(rng, TN + 7)  # deliberately not a tile multiple
    padded = pad_batch(batch, 2 * TN)
    base = GLMObjective(loss=LOSSES["logistic"], batch=batch, l2=0.1)
    fused = GLMObjective(
        loss=LOSSES["logistic"], batch=padded, l2=0.1, fused="interpret"
    )
    w = jnp.asarray((rng.standard_normal(D) * 0.1).astype(np.float32))

    from photon_ml_tpu.ops.glm import vg_fn

    @jax.jit
    def run(f, w):
        return f(w)

    v0, g0 = run(vg_fn(base), w)
    v1, g1 = run(vg_fn(fused), w)
    np.testing.assert_allclose(float(v1), float(v0), rtol=2e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-4, atol=1e-4)


def test_fusion_mode_gating(rng, monkeypatch):
    """_fusion_mode: off by default on CPU (auto), on under interpret, and
    never for sparse layouts, tiny batches, or misaligned feature dims."""
    ok = _make_batch(rng, pallas_glm.MIN_FUSED_ROWS)
    monkeypatch.setenv("PHOTON_PALLAS", "auto")
    assert _fusion_mode(ok) is None  # CPU backend
    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    assert _fusion_mode(ok) == "interpret"
    # too few rows
    assert _fusion_mode(_make_batch(rng, 512)) is None
    # misaligned feature dim
    assert _fusion_mode(_make_batch(rng, pallas_glm.MIN_FUSED_ROWS, d=200)) is None
    # f64 batch (x64 test mode)
    assert _fusion_mode(_make_batch(rng, pallas_glm.MIN_FUSED_ROWS, dtype=np.float64)) is None
    monkeypatch.setenv("PHOTON_PALLAS", "off")
    assert _fusion_mode(ok) is None
    monkeypatch.setenv("PHOTON_PALLAS", "bogus")
    with pytest.raises(ValueError):
        _fusion_mode(ok)


@pytest.mark.parametrize("optimizer", ["LBFGS", "TRON"])
def test_end_to_end_solve_matches_unfused(rng, monkeypatch, optimizer):
    """GLMProblem.run with PHOTON_PALLAS=interpret converges to the same model
    as the jnp path — the full solver loop (L-BFGS line search / TRON CG)
    driving the fused kernels."""
    n = pallas_glm.MIN_FUSED_ROWS
    batch = _make_batch(rng, n)
    problem = GLMProblem(
        task="logistic_regression",
        config=GLMOptimizationConfig(
            optimizer=OptimizerConfig(
                optimizer_type=optimizer, tolerance=1e-9, max_iterations=60
            ),
            regularization=RegularizationContext("L2"),
            reg_weight=1.0,
            variance_type="SIMPLE",
        ),
    )
    monkeypatch.setenv("PHOTON_PALLAS", "off")
    m0, r0 = problem.run(batch)
    monkeypatch.setenv("PHOTON_PALLAS", "interpret")
    m1, r1 = problem.run(batch)
    np.testing.assert_allclose(
        np.asarray(m1.coefficients.means),
        np.asarray(m0.coefficients.means),
        rtol=1e-3,
        atol=1e-5,
    )
    # variances come from the (unfused) hessian_diagonal on the padded batch;
    # weight-0 padding rows must not change them
    np.testing.assert_allclose(
        np.asarray(m1.coefficients.variances),
        np.asarray(m0.coefficients.variances),
        rtol=1e-3,
        atol=1e-6,
    )
