"""Static-analysis suite tests: one fixture trio per rule (fires /
suppressed / clean), suppression + baseline mechanics, config loading, the
CLI, and the runtime transfer guard."""

import json
import os
import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.analysis import (
    LintConfig,
    allow_transfers,
    analyze_paths,
    analyze_source,
    guard_level,
    load_baseline,
    load_config,
    logged_fetch,
    transfer_guard,
    write_baseline,
)
from photon_ml_tpu.analysis.cli import JSON_SCHEMA_VERSION, main as lint_main
from photon_ml_tpu.analysis.engine import write_refusal_inventory
from photon_ml_tpu.analysis.project import (
    analyze_project,
    fragment_matches_template,
)
from photon_ml_tpu.analysis.rules import RULES, explain_rule

HOT = "photon_ml_tpu/game/descent.py"  # matches default hot_loop_modules
COLD = "photon_ml_tpu/models/somewhere.py"
OPS = "photon_ml_tpu/ops/somekernel.py"  # matches default dtype_strict


def findings(src, relpath=COLD, **kwargs):
    return analyze_source(textwrap.dedent(src), relpath, **kwargs)


def rules_of(fs):
    return [f.rule for f in fs if f.active]


# ---------------------------------------------------------------- R1


R1_SRC = """
    import jax.numpy as jnp

    def f(scores):
        s = jnp.sum(scores)
        return float(s)
    """


def test_r1_fires_in_hot_module():
    fs = findings(R1_SRC, HOT)
    assert rules_of(fs) == ["R1"]
    assert fs[0].code == "return float(s)"


def test_r1_silent_outside_hot_modules():
    assert rules_of(findings(R1_SRC, COLD)) == []


def test_r1_suppressed_inline():
    src = """
    import jax.numpy as jnp

    def f(scores):
        s = jnp.sum(scores)
        return float(s)  # photon: ignore[R1]
    """
    fs = findings(src, HOT)
    assert rules_of(fs) == []
    assert [f.rule for f in fs if f.suppressed] == ["R1"]


def test_r1_clean_via_explicit_device_get():
    src = """
    import jax
    import jax.numpy as jnp

    def f(scores):
        s = jnp.sum(scores)
        return float(jax.device_get(s))
    """
    assert rules_of(findings(src, HOT)) == []


def test_r1_np_asarray_on_device_value():
    src = """
    import jax.numpy as jnp
    import numpy as np

    def f(scores):
        s = jnp.cumsum(scores)
        return np.asarray(s)
    """
    assert rules_of(findings(src, HOT)) == ["R1"]


def test_r1_annotation_taint_and_item():
    src = """
    import jax

    def f(x: jax.Array):
        return x.item()
    """
    assert rules_of(findings(src, HOT)) == ["R1"]


def test_r1_host_attrs_stop_taint():
    src = """
    import jax.numpy as jnp

    def f(scores):
        s = jnp.sum(scores)
        return float(s.shape[0])
    """
    assert rules_of(findings(src, HOT)) == []


# ---------------------------------------------------------------- R2


def test_r2_branch_on_tracer_in_jit():
    src = """
    import jax

    @jax.jit
    def f(x: jax.Array):
        if x > 0:
            return x
        return -x
    """
    assert rules_of(findings(src)) == ["R2"]


def test_r2_array_valued_static():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnums=(0,))
    def f(x: jax.Array):
        return x
    """
    assert rules_of(findings(src)) == ["R2"]


def test_r2_fstring_of_tracer():
    src = """
    import jax

    @jax.jit
    def f(x: jax.Array):
        print(f"value is {x}")
        return x
    """
    assert rules_of(findings(src)) == ["R2"]


def test_r2_clean_jit():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def f(x: jax.Array, n: int):
        if n > 3:
            return x * n
        return x
    """
    assert rules_of(findings(src)) == []


# ---------------------------------------------------------------- R3


def test_r3_hardcoded_itemsize():
    src = """
    def block_bytes(n_rows, n_cols):
        total_bytes = n_rows * n_cols * 4
        return total_bytes
    """
    assert rules_of(findings(src)) == ["R3"]


def test_r3_itemsize_needs_byte_context():
    # a bare * 4 with no bytes/itemsize/budget context is not an itemsize
    src = """
    def quadruple(n):
        total = n * 4
        return total
    """
    assert rules_of(findings(src)) == []


def test_r3_float32_literal_cast():
    src = """
    import numpy as np

    def f(x):
        return x.astype(np.float32)
    """
    assert rules_of(findings(src)) == ["R3"]


def test_r3_dtype_strict_asarray():
    src = """
    import jax.numpy as jnp

    def f(x):
        return jnp.asarray(x)
    """
    assert rules_of(findings(src, OPS)) == ["R3"]
    assert rules_of(findings(src, COLD)) == []  # only strict modules


def test_r3_asarray_with_dtype_clean():
    src = """
    import jax.numpy as jnp
    import numpy as np

    def f(x):
        return jnp.asarray(x, np.int32)
    """
    assert rules_of(findings(src, OPS)) == []


# ---------------------------------------------------------------- R4


def test_r4_swallowing_handler():
    src = """
    def f():
        try:
            work()
        except Exception:
            pass
    """
    assert rules_of(findings(src)) == ["R4"]


def test_r4_bare_except():
    src = """
    def f():
        try:
            work()
        except:
            log()
    """
    assert rules_of(findings(src)) == ["R4"]


def test_r4_counted_handler_clean():
    src = """
    def f():
        try:
            work()
        except Exception:
            obs.swallowed_error("site")
    """
    assert rules_of(findings(src)) == []


def test_r4_reraising_handler_clean():
    src = """
    def f():
        try:
            work()
        except Exception as e:
            log(e)
            raise
    """
    assert rules_of(findings(src)) == []


def test_r4_narrow_handler_clean():
    src = """
    def f():
        try:
            work()
        except ValueError:
            pass
    """
    assert rules_of(findings(src)) == []


# ---------------------------------------------------------------- R5

ATOMIC = "photon_ml_tpu/io/somewriter.py"  # matches default atomic_write


R5_SRC = """
    def f(path, doc):
        with open(path, "w") as fh:
            fh.write(doc)
    """


def test_r5_fires_in_atomic_write_module():
    fs = findings(R5_SRC, ATOMIC)
    assert rules_of(fs) == ["R5"]
    assert "atomic_write" in fs[0].message


def test_r5_fires_in_robust_package():
    assert rules_of(findings(R5_SRC, "photon_ml_tpu/robust/newmod.py")) == ["R5"]


def test_r5_silent_outside_atomic_modules():
    assert rules_of(findings(R5_SRC, COLD)) == []


def test_r5_read_mode_clean():
    src = """
    def f(path):
        with open(path, "rb") as fh:
            return fh.read()
    """
    assert rules_of(findings(src, ATOMIC)) == []


def test_r5_flags_append_exclusive_and_update_modes():
    src = """
    def f(path):
        a = open(path, "ab")
        b = open(path, mode="x")
        c = open(path, "r+")
    """
    assert rules_of(findings(src, ATOMIC)) == ["R5", "R5", "R5"]


def test_r5_nonliteral_mode_flagged():
    src = """
    def f(path, mode):
        return open(path, mode)
    """
    fs = findings(src, ATOMIC)
    assert rules_of(fs) == ["R5"]
    assert "non-literal mode" in fs[0].message


def test_r5_suppressed_inline():
    src = """
    def f(path):
        # photon: ignore[R5] — append-only event log, rename would truncate
        return open(path, "a")
    """
    fs = findings(src, ATOMIC)
    assert rules_of(fs) == []
    assert [f.rule for f in fs if f.suppressed] == ["R5"]


def test_r5_clean_via_atomic_write():
    src = """
    from photon_ml_tpu.robust.atomic import atomic_write

    def f(path, doc):
        with atomic_write(path, "w") as fh:
            fh.write(doc)
    """
    assert rules_of(findings(src, ATOMIC)) == []


# ---------------------------------------------------------------- R6


def test_r6_nan_compare_fires_anywhere():
    src = """
    import jax.numpy as jnp
    import numpy as np
    import math

    def f(x):
        if x == jnp.nan:
            return 0
        if x != np.nan:
            return 1
        if x == math.nan:
            return 2
        if x == float("nan"):
            return 3
    """
    fs = findings(src, COLD)  # unconditional: fires outside hot modules too
    assert rules_of(fs) == ["R6"] * 4
    assert "IEEE 754" in fs[0].message


def test_r6_ordinary_compares_clean():
    src = """
    import jax.numpy as jnp

    def f(x, nan_count):
        if nan_count == 0 and x != 1.0:
            return jnp.isnan(x)
    """
    assert rules_of(findings(src, COLD)) == []


def test_r6_uncounted_isnan_patch_fires_in_hot_module():
    src = """
    import jax.numpy as jnp

    def patch(x):
        return jnp.where(jnp.isnan(x), 0.0, x)
    """
    fs = findings(src, HOT)
    assert rules_of(fs) == ["R6"]
    assert "counter" in fs[0].message


def test_r6_isnan_patch_silent_outside_hot_modules():
    src = """
    import jax.numpy as jnp

    def patch(x):
        return jnp.where(jnp.isnan(x), 0.0, x)
    """
    assert rules_of(findings(src, COLD)) == []


def test_r6_counted_isnan_patch_clean():
    src = """
    import jax.numpy as jnp
    from photon_ml_tpu import obs

    def patch(x):
        y = jnp.where(jnp.isnan(x), 0.0, x)
        obs.current_run().registry.counter("c", "h").inc()
        return y
    """
    assert rules_of(findings(src, HOT)) == []


def test_r6_isfinite_where_clean():
    src = """
    import jax.numpy as jnp

    def commit(x, new):
        return jnp.where(jnp.isfinite(new), new, x)
    """
    assert rules_of(findings(src, HOT)) == []


def test_r6_suppressed_inline():
    src = """
    import jax.numpy as jnp

    def patch(x):
        # photon: ignore[R6] — display-only path, NaNs already counted upstream
        return jnp.where(jnp.isnan(x), 0.0, x)
    """
    fs = findings(src, HOT)
    assert rules_of(fs) == []
    assert [f.rule for f in fs if f.suppressed] == ["R6"]


# ---------------------------------------------------------------- R7

TIMING = "photon_ml_tpu/game/problem.py"  # timing-strict but not hot

R7_SRC = """
    import time

    def solve():
        t0 = time.perf_counter()
        work()
        return time.perf_counter() - t0
    """


def test_r7_fires_in_timing_strict_module():
    fs = findings(R7_SRC, TIMING)
    assert rules_of(fs) == ["R7", "R7"]
    assert "obs.span" in fs[0].message


def test_r7_silent_outside_timing_strict_modules():
    assert rules_of(findings(R7_SRC, COLD)) == []


def test_r7_catches_aliased_imports():
    src = """
    import time as _time
    from time import perf_counter

    def solve():
        a = _time.time()
        b = perf_counter()
        c = _time.monotonic()
    """
    assert rules_of(findings(src, TIMING)) == ["R7", "R7", "R7"]


def test_r7_span_and_timed_clean():
    src = """
    from photon_ml_tpu import obs
    from photon_ml_tpu.utils.timed import timed

    def solve():
        with obs.span("solve", phase="solve") as sp:
            work()
        with timed("score"):
            work()
        return sp.duration_s
    """
    assert rules_of(findings(src, TIMING)) == []


def test_r7_suppressed_inline():
    src = """
    import time

    def submit(q, req):
        # photon: ignore[R7] — cross-thread enqueue stamp, cannot be a span
        q.put((req, time.perf_counter()))
    """
    fs = findings(src, TIMING)
    assert rules_of(fs) == []
    assert [f.rule for f in fs if f.suppressed] == ["R7"]


# ---------------------------------------------------------------- R8

JAXFREE = "photon_ml_tpu/obs/report.py"  # matches default jax_free_modules

R8_SRC = """
    import jax
    import jax.numpy as jnp
    from jax import tree_util
    """


def test_r8_fires_in_jax_free_module():
    fs = findings(R8_SRC, JAXFREE)
    assert rules_of(fs) == ["R8", "R8", "R8"]
    assert "jax-free" in fs[0].message


def test_r8_silent_outside_jax_free_modules():
    assert rules_of(findings(R8_SRC, COLD)) == []


def test_r8_allows_function_level_and_type_checking_imports():
    src = """
    from typing import TYPE_CHECKING

    if TYPE_CHECKING:
        import jax

    def fetch(x):
        import jax.numpy as jnp

        return jnp.asarray(x)
    """
    assert rules_of(findings(src, JAXFREE)) == []


def test_r8_catches_try_guarded_and_nested_module_imports():
    src = """
    try:
        import jax
    except ImportError:
        jax = None

    if True:
        from jax.experimental import mesh_utils
    """
    assert rules_of(findings(src, JAXFREE)) == ["R8", "R8"]


def test_r8_suppressed_inline():
    src = """
    import jax  # photon: ignore[R8]
    """
    fs = findings(src, JAXFREE)
    assert rules_of(fs) == []
    assert [f.rule for f in fs if f.suppressed] == ["R8"]


def test_r8_report_path_modules_lint_clean():
    """The shipped jax-free modules must satisfy their own rule."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    config = load_config(os.path.join(root, "pyproject.toml"))
    for rel in (
        "photon_ml_tpu/obs/report.py",
        "photon_ml_tpu/obs/diagnostics.py",
        "photon_ml_tpu/obs/memory.py",
        "photon_ml_tpu/cli/report.py",
        "photon_ml_tpu/io/__init__.py",
    ):
        assert config.is_jax_free(rel), rel
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            src = f.read()
        fs = analyze_source(src, rel, config=config, rules=["R8"])
        assert [f.rule for f in fs if f.active] == [], rel


# ----------------------------------------------------- suppression mechanics


def test_standalone_comment_suppresses_next_line():
    src = """
    def f():
        try:
            work()
        # photon: ignore[R4] — close() failures are best-effort by contract
        except Exception:
            pass
    """
    fs = findings(src)
    assert rules_of(fs) == []
    assert [f.rule for f in fs if f.suppressed] == ["R4"]


def test_docstring_mention_does_not_suppress():
    src = '''
    def f():
        """Suppress with  # photon: ignore[R4]  on the offending line."""
        try:
            work()
        except Exception:
            pass
    '''
    assert rules_of(findings(src)) == ["R4"]


def test_unknown_rule_in_ignore_is_an_error():
    src = """
    x = 1  # photon: ignore[R99]
    """
    with pytest.raises(ValueError, match="unknown rule"):
        findings(src)


# ---------------------------------------------------------------- baseline


BAD_MODULE = textwrap.dedent(
    """
    def f():
        try:
            work()
        except Exception:
            pass
    """
)


def _mini_repo(tmp_path, source=BAD_MODULE):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(source)
    return LintConfig(paths=("pkg",), root=str(tmp_path))


def test_baseline_roundtrip(tmp_path):
    cfg = _mini_repo(tmp_path)
    result = analyze_paths(config=cfg)
    assert [f.rule for f in result.active] == ["R4"]

    write_baseline(result.findings, cfg.baseline_path)
    baseline = load_baseline(cfg.baseline_path)
    again = analyze_paths(config=cfg, baseline=baseline)
    assert again.active == []
    assert [f.rule for f in again.findings if f.baselined] == ["R4"]


def test_baseline_is_a_multiset(tmp_path):
    cfg = _mini_repo(tmp_path)
    write_baseline(analyze_paths(config=cfg).findings, cfg.baseline_path)
    # a SECOND identical offending handler is NOT grandfathered
    (tmp_path / "pkg" / "mod.py").write_text(BAD_MODULE + BAD_MODULE.replace("f()", "g()"))
    result = analyze_paths(config=cfg, baseline=load_baseline(cfg.baseline_path))
    assert len([f for f in result.findings if f.rule == "R4"]) == 2
    assert len(result.active) == 1


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == {}


# ---------------------------------------------------------------- config


def test_load_config_from_pyproject(tmp_path):
    py = tmp_path / "pyproject.toml"
    py.write_text(
        textwrap.dedent(
            """
            [project]
            name = "x"

            [tool.photon-lint]
            paths = ["pkg"]
            baseline = "base.json"
            hot_loop_modules = [
                "pkg/hot.py",  # trailing comment
                "pkg/loops/*",
            ]
            """
        )
    )
    cfg = load_config(pyproject=str(py))
    assert cfg.paths == ("pkg",)
    assert cfg.baseline == "base.json"
    assert cfg.hot_loop_modules == ("pkg/hot.py", "pkg/loops/*")
    assert cfg.is_hot("pkg/loops/inner.py")
    assert not cfg.is_hot("pkg/cold.py")
    assert cfg.root == str(tmp_path)


def test_load_config_rejects_unknown_keys(tmp_path):
    py = tmp_path / "pyproject.toml"
    py.write_text("[tool.photon-lint]\ntypo_key = 1\n")
    with pytest.raises(ValueError, match="unknown keys"):
        load_config(pyproject=str(py))


# ---------------------------------------------------------------- CLI


def _write_pyproject(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.photon-lint]\npaths = ["pkg"]\n'
    )
    return str(tmp_path / "pyproject.toml")


def test_cli_exit_codes_and_json(tmp_path, capsys):
    _mini_repo(tmp_path)
    py = _write_pyproject(tmp_path)

    assert lint_main(["--config", py, "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["active"] == 1 and not report["ok"]
    assert report["findings"][0]["rule"] == "R4"

    assert lint_main(["--config", py, "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--config", py]) == 0  # baselined now
    assert "baselined" in capsys.readouterr().out

    # a clean tree exits 0 with no baseline at all
    (tmp_path / "pkg" / "mod.py").write_text("x = 1\n")
    os.remove(tmp_path / "lint_baseline.json")
    assert lint_main(["--config", py]) == 0


def test_cli_rule_filter(tmp_path, capsys):
    _mini_repo(tmp_path)
    py = _write_pyproject(tmp_path)
    assert lint_main(["--config", py, "--rule", "R1"]) == 0
    assert lint_main(["--config", py, "--rule", "R4"]) == 1
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("R1", "R2", "R3", "R4"):
        assert rule in out


def test_cli_parse_error_fails(tmp_path, capsys):
    _mini_repo(tmp_path, source="def broken(:\n")
    py = _write_pyproject(tmp_path)
    assert lint_main(["--config", py]) == 1
    assert "parse error" in capsys.readouterr().err


# ------------------------------------------------- R9 (cross-thread races)


def proj(sources, rules=("R9",), config=None):
    return analyze_project(
        {rel: textwrap.dedent(src) for rel, src in sources.items()},
        config or LintConfig(),
        rules=rules,
    )


RACY_WORKER = {
    "pkg/worker.py": """
    import threading

    class Worker:
        def __init__(self):
            self.value = 0
            self.thread = threading.Thread(target=self._work)

        def start(self):
            self.thread.start()

        def _work(self):
            self.value = 1

        def read(self):
            return self.value
    """
}


def test_r9_flags_unguarded_cross_thread_write():
    res = proj(RACY_WORKER)
    assert [f.rule for f in res.findings] == ["R9"]
    assert "Worker.value" in res.findings[0].message
    assert "no common lock" in res.findings[0].message
    assert res.errors == []


def test_r9_lock_on_both_sides_is_clean():
    res = proj(
        {
            "pkg/worker.py": """
            import threading

            class Worker:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.value = 0
                    self.thread = threading.Thread(target=self._work)

                def start(self):
                    self.thread.start()

                def _work(self):
                    with self.lock:
                        self.value = 1

                def read(self):
                    with self.lock:
                        return self.value
            """
        }
    )
    assert res.findings == [] and res.errors == []


def test_r9_guarded_by_annotation_excuses_and_is_marked_used():
    res = proj(
        {
            "pkg/worker.py": """
            import threading

            class Worker:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.value = 0  # photon: guarded-by[lock]
                    self.thread = threading.Thread(target=self._work)

                def start(self):
                    self.thread.start()

                def _work(self):
                    self.value = 1

                def read(self):
                    return self.value
            """
        }
    )
    assert res.findings == [] and res.errors == []
    assert res.used_annotations == {("pkg/worker.py", 7)}


def test_r9_thread_confined_annotation_excuses():
    src = dict(RACY_WORKER)
    src["pkg/worker.py"] = src["pkg/worker.py"].replace(
        "self.value = 0", "self.value = 0  # photon: thread-confined"
    )
    res = proj(src)
    assert res.findings == [] and res.errors == []
    assert len(res.used_annotations) == 1


def test_r9_guarded_by_unknown_lock_is_an_error():
    src = dict(RACY_WORKER)
    src["pkg/worker.py"] = src["pkg/worker.py"].replace(
        "self.value = 0", "self.value = 0  # photon: guarded-by[nope]"
    )
    res = proj(src)
    assert any("names no lock attribute" in e for e in res.errors)


def test_r9_unattached_annotation_is_an_error():
    res = proj(
        {
            "pkg/mod.py": """
            def f():
                x = 1  # photon: thread-confined
                return x
            """
        }
    )
    assert any("not attached" in e for e in res.errors)


SVC = {
    "pkg/svc.py": """
    class Svc:
        def __init__(self):
            self.state = 0

        def _poll(self):
            self.state = 1

        def read(self):
            return self.state
    """
}


def test_r9_thread_entrypoints_create_worker_roots():
    # without configuration _poll has no callers and no spawn site, so it
    # conservatively seeds the main context: no conflict
    assert proj(SVC).findings == []
    cfg = LintConfig(thread_entrypoints=("pkg/svc.py::Svc._poll",))
    res = proj(SVC, config=cfg)
    assert [f.rule for f in res.findings] == ["R9"]
    assert "Svc.state" in res.findings[0].message


def test_r9_unknown_thread_entrypoint_is_an_error():
    cfg = LintConfig(thread_entrypoints=("pkg/svc.py::Nope.f",))
    res = proj(SVC, config=cfg)
    assert any("thread_entrypoints" in e for e in res.errors)


def test_r9_submitted_callable_runs_in_pool_context():
    res = proj(
        {
            "pkg/pool.py": """
            from concurrent.futures import ThreadPoolExecutor

            class Job:
                def __init__(self, pool):
                    self.progress = 0
                    self.pool = pool

                def start(self):
                    self.pool.submit(self._run)

                def _run(self):
                    self.progress = 1

                def read(self):
                    return self.progress
            """
        }
    )
    assert [f.rule for f in res.findings] == ["R9"]


# ------------------------------------------- R10 (refusal-ledger contract)


LEDGER_HEADER = (
    "| refused combination | message contains | raised at |\n"
    "|---|---|---|\n"
)

R10_MODULE = '''
def solve(mode):
    if mode == "box":
        raise ValueError(
            "lbfgs with box constraints is not supported; use tron"
        )
'''

R10_PINS = """
CASES = [
    ("lbfgs-box", "lbfgs with box constraints is not supported", ValueError),
]
"""


def _r10_repo(
    tmp_path,
    ledger_rows="| lbfgs + box | `lbfgs with box constraints is not supported` | `pkg/mod.py` |\n",
    pins=R10_PINS,
    module_src=R10_MODULE,
):
    (tmp_path / "README.md").write_text(LEDGER_HEADER + ledger_rows)
    (tmp_path / "tests").mkdir(exist_ok=True)
    (tmp_path / "tests" / "test_support_matrix.py").write_text(pins)
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(textwrap.dedent(module_src))
    cfg = LintConfig(paths=("pkg",), root=str(tmp_path))
    sources = {"pkg/mod.py": textwrap.dedent(module_src)}
    return cfg, sources


def _r10(cfg, sources):
    return analyze_project(sources, cfg, rules=("R10",))


def test_r10_consistent_repo_is_clean(tmp_path):
    cfg, sources = _r10_repo(tmp_path)
    path, n = write_refusal_inventory(cfg)
    assert n == 1 and os.path.isfile(path)
    res = _r10(cfg, sources)
    assert res.findings == []
    assert res.refusal_inventory["refusals"][0]["modules"] == ["pkg/mod.py"]
    assert res.refusal_inventory["refusals"][0]["exceptions"] == ["ValueError"]


def test_r10_ledger_fragment_with_no_raise_site(tmp_path):
    cfg, sources = _r10_repo(
        tmp_path,
        ledger_rows=(
            "| lbfgs + box | `lbfgs with box constraints is not supported` | `pkg/mod.py` |\n"
            "| ghost | `this refusal is enforced nowhere` | `pkg/mod.py` |\n"
        ),
    )
    write_refusal_inventory(cfg)
    msgs = [f.message for f in _r10(cfg, sources).findings]
    assert any("matches no raise site" in m for m in msgs)
    # the unenforced row is also unpinned
    assert any("pinned by no" in m for m in msgs)


def test_r10_pin_with_no_ledger_row(tmp_path):
    cfg, sources = _r10_repo(
        tmp_path,
        pins=R10_PINS.replace(
            "]\n",
            '    ("ghost", "pinned but undocumented", ValueError),\n]\n',
        ),
    )
    write_refusal_inventory(cfg)
    res = _r10(cfg, sources)
    assert [f.file for f in res.findings] == ["tests/test_support_matrix.py"]
    assert "appears in no refusal-ledger row" in res.findings[0].message


def test_r10_refusal_phrased_raise_must_be_documented(tmp_path):
    cfg, sources = _r10_repo(
        tmp_path,
        module_src=R10_MODULE
        + '''

def other(layout):
    if layout == "tiled":
        raise ValueError(f"layout {layout} is not supported here")
''',
    )
    write_refusal_inventory(cfg)
    res = _r10(cfg, sources)
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f.file == "pkg/mod.py"
    assert "matches no refusal-ledger row" in f.message


def test_r10_inventory_staleness_is_byte_exact(tmp_path):
    cfg, sources = _r10_repo(tmp_path)
    msgs = [f.message for f in _r10(cfg, sources).findings]
    assert any("refusal inventory is missing" in m for m in msgs)
    path, _ = write_refusal_inventory(cfg)
    assert _r10(cfg, sources).findings == []
    with open(path, "a") as f:
        f.write("\n")
    msgs = [f.message for f in _r10(cfg, sources).findings]
    assert any("refusal inventory is stale" in m for m in msgs)


def test_r10_skipped_without_a_ledger(tmp_path):
    cfg = LintConfig(paths=("pkg",), root=str(tmp_path))
    res = analyze_project(
        {"pkg/mod.py": textwrap.dedent(R10_MODULE)}, cfg, rules=("R10",)
    )
    assert res.findings == [] and res.refusal_inventory is None


def test_fragment_matches_template():
    segs = ["solver ", None, " does not support box constraints"]
    assert fragment_matches_template("does not support box", segs)
    assert fragment_matches_template("solver", segs)
    # a match may span a placeholder when anchored in a literal
    assert fragment_matches_template("solver lbfgs does not support", segs)
    assert not fragment_matches_template("zzz", segs)
    # a match living entirely inside a placeholder is vacuous
    assert not fragment_matches_template("anything", ["prefix ", None])
    # divergence inside a literal is a non-match
    assert not fragment_matches_template("does not support ropes", segs)


# ------------------------------------------------ R11 (metric contract)


def _r11(src, config=None):
    cfg = config or LintConfig(root="/nonexistent", metric_docs=())
    res = analyze_project(
        {"pkg/m.py": textwrap.dedent(src)}, cfg, rules=("R11",)
    )
    return res.findings


def test_r11_counter_must_end_total():
    fs = _r11(
        """
        def f(reg):
            reg.counter("photon_foo", "help").inc()
        """
    )
    assert ["must end in _total" in f.message for f in fs] == [True]


def test_r11_non_counter_must_not_end_total():
    fs = _r11(
        """
        def f(reg):
            reg.gauge("photon_bar_total", "help").set(1)
        """
    )
    assert ["must not end in _total" in f.message for f in fs] == [True]


def test_r11_one_kind_per_family():
    fs = _r11(
        """
        def f(reg):
            reg.counter("photon_x_total", "help").inc()

        def g(reg):
            reg.gauge("photon_x_total", "help").set(1)
        """
    )
    assert any("one family, one kind" in f.message for f in fs)


def test_r11_label_sets_must_agree():
    fs = _r11(
        """
        def f(reg):
            reg.counter("photon_x_total", "help").labels(site=1).inc()

        def g(reg):
            reg.counter("photon_x_total", "help").labels(kind=2).inc()
        """
    )
    assert any("label keys must agree" in f.message for f in fs)


def test_r11_reserved_suffixes_rejected():
    fs = _r11(
        """
        def f(reg):
            reg.gauge("photon_x_count", "help").set(1)
        """
    )
    assert any("reserves" in f.message for f in fs)


def test_r11_consistent_family_is_clean():
    assert (
        _r11(
            """
            def f(reg):
                reg.counter("photon_x_total", "help").labels(site=1).inc()

            def g(reg):
                reg.counter("photon_x_total", "help").labels(site=2).inc(3)
            """
        )
        == []
    )


def test_r11_doc_drift_both_directions(tmp_path):
    (tmp_path / "METRICS.md").write_text(
        "documented: photon_a_total and photon_c_total\n"
    )
    cfg = LintConfig(root=str(tmp_path), metric_docs=("METRICS.md",))
    fs = _r11(
        """
        def f(reg):
            reg.counter("photon_a_total", "help").inc()
            reg.counter("photon_b_total", "help").inc()
        """,
        config=cfg,
    )
    msgs = [f.message for f in fs]
    assert any(
        "'photon_b_total' is not documented" in m for m in msgs
    )
    assert any(
        "'photon_c_total' is registered nowhere" in m for m in msgs
    )
    assert not any("photon_a_total" in m for m in msgs)


def test_r11_dynamic_prefix_families_match_docs_by_prefix(tmp_path):
    src = """
    def f(reg, direction):
        reg.counter(
            f"photon_dev_{direction}_bytes_total", "help"
        ).labels(site="x").inc()
    """
    (tmp_path / "METRICS.md").write_text("photon_dev_fetch_bytes_total\n")
    cfg = LintConfig(root=str(tmp_path), metric_docs=("METRICS.md",))
    assert _r11(src, config=cfg) == []
    # without a doc token under the prefix, the dynamic family is flagged
    (tmp_path / "METRICS.md").write_text("no metrics here\n")
    fs = _r11(src, config=cfg)
    assert any("dynamically-named metric family" in f.message for f in fs)


# --------------------------------------------- R12 (unused suppressions)


def test_r12_unused_suppression_flagged_on_full_runs(tmp_path):
    cfg = _mini_repo(tmp_path, "x = 1  # photon: ignore[R4]\n")
    result = analyze_paths(config=cfg)  # configured run -> project passes
    assert [f.rule for f in result.active] == ["R12"]
    assert "suppresses no finding" in result.active[0].message
    # explicit file-subset runs stay per-file: no R12 there
    assert analyze_paths(paths=("pkg",), config=cfg).active == []


def test_r12_used_suppression_is_not_flagged(tmp_path):
    cfg = _mini_repo(
        tmp_path,
        BAD_MODULE.replace(
            "except Exception:", "except Exception:  # photon: ignore[R4]"
        ),
    )
    assert analyze_paths(config=cfg).active == []


def test_r12_unused_annotation_flagged(tmp_path):
    cfg = _mini_repo(
        tmp_path,
        textwrap.dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self.lock = threading.Lock()
                    self.x = 0  # photon: thread-confined
            """
        ),
    )
    result = analyze_paths(config=cfg)
    assert [f.rule for f in result.active] == ["R12"]
    assert "annotation suppresses no R9 finding" in result.active[0].message


# --------------------------------------------------- explain / schema


def test_every_rule_has_an_explanation():
    for rule in RULES:
        text = explain_rule(rule)
        assert rule in text
        assert "bad" in text.lower() and "good" in text.lower()


def test_cli_explain(capsys):
    assert lint_main(["--explain", "R9"]) == 0
    out = capsys.readouterr().out
    assert "R9" in out and "guarded-by" in out


def test_cli_json_carries_schema_version(tmp_path, capsys):
    _mini_repo(tmp_path)
    py = _write_pyproject(tmp_path)
    assert lint_main(["--config", py, "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["schema_version"] == JSON_SCHEMA_VERSION


def test_cli_write_refusal_inventory(tmp_path, capsys):
    cfg, _ = _r10_repo(tmp_path)
    py = tmp_path / "pyproject.toml"
    py.write_text(
        textwrap.dedent(
            """
            [tool.photon-lint]
            paths = ["pkg"]
            """
        )
    )
    assert lint_main(["--config", str(py), "--write-refusal-inventory"]) == 0
    out = capsys.readouterr().out
    assert "wrote 1 refusal(s)" in out
    inv = json.loads((tmp_path / "refusals.json").read_text())
    assert inv["refusals"][0]["modules"] == ["pkg/mod.py"]


# ------------------------------------------------------------ runtime guard


def _backend_enforces_guard() -> bool:
    """XLA only intercepts device->host copies that are real DMAs; on the
    CPU backend device buffers alias host memory and the guard is a no-op."""
    import jax

    x = jnp.arange(4, dtype=jnp.float32)
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            float(jnp.sum(x))
    except Exception:
        return True
    return False


@pytest.mark.skipif(
    not _backend_enforces_guard(),
    reason="backend does not route d2h through the transfer guard (CPU zero-copy)",
)
def test_transfer_guard_blocks_implicit_fetch():
    x = jnp.arange(8, dtype=jnp.float32)
    with transfer_guard():
        with pytest.raises(Exception, match="[Dd]isallow"):
            float(jnp.sum(x))


def test_transfer_guard_level_plumbing():
    assert guard_level() is None
    with transfer_guard():
        assert guard_level() == "disallow"
        with allow_transfers():
            assert guard_level() == "allow"
        assert guard_level() == "disallow"
    assert guard_level() is None


def test_transfer_guard_level_restored_on_error():
    with pytest.raises(RuntimeError):
        with transfer_guard():
            raise RuntimeError("boom")
    assert guard_level() is None


def test_logged_fetch_allowed_and_counted_under_guard():
    x = jnp.arange(8, dtype=jnp.float32)
    with obs.use_run(obs.RunTelemetry()) as run:
        with transfer_guard():
            out = logged_fetch("test.fetch", x)
        assert isinstance(out, np.ndarray)
        assert out.nbytes == 32
        snap = {
            (m["name"], m["labels"].get("site")): m["value"]
            for m in run.registry.snapshot()
        }
        assert snap[("photon_device_fetch_bytes_total", "test.fetch")] == 32.0


def test_logged_fetch_numpy_passthrough_uncounted():
    a = np.arange(4.0)
    with obs.use_run(obs.RunTelemetry()) as run:
        out = logged_fetch("test.noop", a)
        assert out is a
        assert run.registry.snapshot() == []


def test_allow_transfers_lifts_guard():
    x = jnp.ones((3,))
    with transfer_guard():
        with allow_transfers():
            assert float(jnp.sum(x)) == 3.0


def test_guard_env_override_off(monkeypatch):
    monkeypatch.setenv("PHOTON_TRANSFER_GUARD", "off")
    x = jnp.ones((2,))
    with transfer_guard():
        assert guard_level() == "allow"
        assert float(jnp.sum(x)) == 2.0


def test_guard_env_override_invalid(monkeypatch):
    monkeypatch.setenv("PHOTON_TRANSFER_GUARD", "sideways")
    with pytest.raises(ValueError, match="PHOTON_TRANSFER_GUARD"):
        with transfer_guard():
            pass


# ------------------------------------------- R13 (lock-order deadlock)


DEADLOCK = {
    "pkg/locks.py": """
    import threading

    L1 = threading.Lock()
    L2 = threading.Lock()


    def f():
        with L1:
            with L2:
                pass


    def h():
        with L1:
            pass


    def g():
        with L2:
            h()
    """
}


def test_r13_flags_lock_order_cycle():
    res = proj(DEADLOCK, rules=("R13",))
    assert [f.rule for f in res.findings] == ["R13"]
    msg = res.findings[0].message
    assert "lock-order cycle" in msg
    assert "pkg.locks.L1" in msg and "pkg.locks.L2" in msg
    assert "inside h()" in msg  # the interprocedural witness
    assert res.errors == []


def test_r13_lock_order_annotation_excuses_the_vouched_edge():
    src = DEADLOCK["pkg/locks.py"].replace(
        "def f():",
        "# photon: lock-order[L1 < L2]\n    def f():",
    )
    res = proj({"pkg/locks.py": src}, rules=("R13",))
    assert res.findings == []
    assert res.errors == []
    assert res.used_annotations  # consumed, so R12 stays quiet


def test_r13_malformed_annotation_is_a_config_error():
    src = DEADLOCK["pkg/locks.py"].replace(
        "def f():",
        "# photon: lock-order[L1 >> L2]\n    def f():",
    )
    res = proj({"pkg/locks.py": src}, rules=("R13",))
    assert any(
        e.startswith("annotation:") and "malformed" in e for e in res.errors
    )


def test_r13_unknown_lock_name_is_an_error():
    src = DEADLOCK["pkg/locks.py"].replace(
        "def f():",
        "# photon: lock-order[L1 < NOPE]\n    def f():",
    )
    res = proj({"pkg/locks.py": src}, rules=("R13",))
    assert any("unknown lock 'NOPE'" in e and "known:" in e for e in res.errors)


def test_r13_consistent_nesting_is_clean():
    src = DEADLOCK["pkg/locks.py"].replace("with L2:\n            h()", "h()")
    res = proj({"pkg/locks.py": src}, rules=("R13",))
    assert res.findings == []


# ------------------------------------------- R14 (resource lifecycle)


def test_r14_flags_unjoined_thread():
    res = proj(
        {
            "pkg/mod.py": """
            import threading


            def spawn(fn):
                t = threading.Thread(target=fn)
                t.start()
            """
        },
        rules=("R14",),
    )
    assert [f.rule for f in res.findings] == ["R14"]
    assert "thread 't'" in res.findings[0].message
    assert "spawn()" in res.findings[0].message


def test_r14_ownership_transfer_is_clean():
    res = proj(
        {
            "pkg/mod.py": """
            import threading


            def spawn(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t


            def spawn_into(fn, registry):
                t = threading.Thread(target=fn)
                t.start()
                registry.append(t)


            def joined(fn):
                t = threading.Thread(target=fn)
                t.start()
                try:
                    fn()
                finally:
                    t.join()


            def daemon(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
            """
        },
        rules=("R14",),
    )
    assert res.findings == []


def test_r14_flags_exception_path_leak():
    res = proj(
        {
            "pkg/mod.py": """
            import socket


            def serve(path, run):
                sock = socket.socket()
                sock.bind(path)
                with sock:
                    run()
            """
        },
        rules=("R14",),
    )
    assert [f.rule for f in res.findings] == ["R14"]
    assert "exception escapes serve()" in res.findings[0].message


def test_r14_catch_all_cleanup_handler_is_clean():
    res = proj(
        {
            "pkg/mod.py": """
            import socket


            def serve(path, run):
                sock = socket.socket()
                try:
                    sock.bind(path)
                except BaseException:
                    sock.close()
                    raise
                with sock:
                    run()
            """
        },
        rules=("R14",),
    )
    assert res.findings == []


# ------------------------------------------- R15 (jit tracer hazards)


R15_TP = {
    "pkg/mod.py": """
    import jax
    import jax.numpy as jnp


    def helper(x: jax.Array):
        if x > 0:
            return jnp.log(x)
        return x


    @jax.jit
    def f(x: jax.Array):
        return helper(x)
    """
}


def test_r15_flags_branch_in_jit_reachable_helper():
    res = proj(R15_TP, rules=("R15",))
    assert [f.rule for f in res.findings] == ["R15"]
    msg = res.findings[0].message
    assert "Python branch on traced value 'x'" in msg
    assert "helper()" in msg and "reachable from @jit f()" in msg


def test_r15_static_arg_annotation_excuses_the_param():
    res = proj(
        {
            "pkg/mod.py": """
            import jax
            import jax.numpy as jnp


            def helper(x: jax.Array, n: jax.Array):  # photon: static-arg[n]
                if n > 3:
                    return jnp.log(x)
                return x


            @jax.jit
            def f(x: jax.Array, n):
                return helper(x, n)
            """
        },
        rules=("R15",),
    )
    assert res.findings == []
    assert res.errors == []
    assert res.used_annotations


def test_r15_flags_float_coercion_and_host_mutation():
    res = proj(
        {
            "pkg/mod.py": """
            import jax

            count = 0


            def helper(x: jax.Array):
                global count
                count = count + 1
                return float(x) * 2.0


            @jax.jit
            def f(x: jax.Array):
                return helper(x)
            """
        },
        rules=("R15",),
    )
    msgs = sorted(f.message for f in res.findings)
    assert len(msgs) == 2
    assert any("float() coercion" in m for m in msgs)
    assert any("write to closed-over 'count'" in m for m in msgs)


def test_r15_unreachable_helper_is_clean():
    src = R15_TP["pkg/mod.py"].replace("@jax.jit\n", "")
    res = proj({"pkg/mod.py": src}, rules=("R15",))
    assert res.findings == []


def test_r15_static_arg_on_nonparam_is_an_error():
    res = proj(
        {
            "pkg/mod.py": """
            import jax


            def helper(x: jax.Array):  # photon: static-arg[nope]
                return x


            @jax.jit
            def f(x: jax.Array):
                return helper(x)
            """
        },
        rules=("R15",),
    )
    assert any("matches no parameter" in e for e in res.errors)


# ------------------------------------------- R16 (fault-site inventory)


FAULTY_MOD = """
import os
from photon_ml_tpu.robust import faults, io_call


def boundary():
    faults.check("demo.boundary")
"""

FAULT_DOCS = """
# Demo

| Fault site | Injected in | Failure |
|---|---|---|
| `demo.boundary` | `pkg/mod.py` | kill at the boundary |
"""


def _r16_repo(tmp_path, docs=FAULT_DOCS, test_literal='"demo.boundary"'):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(FAULTY_MOD)
    (tmp_path / "README.md").write_text(docs)
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_demo.py").write_text(f"SITE = {test_literal}\n")
    cfg = LintConfig(paths=("pkg",), root=str(tmp_path))
    return cfg, {"pkg/mod.py": FAULTY_MOD}


def test_r16_consistent_repo_is_clean(tmp_path):
    from photon_ml_tpu.analysis.engine import write_fault_inventory

    cfg, sources = _r16_repo(tmp_path)
    path, n = write_fault_inventory(cfg)
    assert n == 1 and os.path.isfile(path)
    res = analyze_project(sources, cfg, rules=("R16",))
    assert res.findings == []


def test_r16_flags_missing_and_stale_inventory(tmp_path):
    from photon_ml_tpu.analysis.engine import write_fault_inventory

    cfg, sources = _r16_repo(tmp_path)
    res = analyze_project(sources, cfg, rules=("R16",))
    assert any("fault inventory is missing" in f.message for f in res.findings)

    path, _ = write_fault_inventory(cfg)
    with open(path, "a") as f:
        f.write("\n")  # byte-compare: even whitespace drift is stale
    res = analyze_project(sources, cfg, rules=("R16",))
    assert any("fault inventory is stale" in f.message for f in res.findings)


def test_r16_flags_undocumented_and_untested_sites(tmp_path):
    cfg, sources = _r16_repo(
        tmp_path, docs="# Demo\n", test_literal='"unrelated"'
    )
    msgs = [
        f.message
        for f in analyze_project(sources, cfg, rules=("R16",)).findings
    ]
    assert any("not documented" in m for m in msgs)
    assert any("no test exercises fault site" in m for m in msgs)


def test_r16_flags_stale_docs_row(tmp_path):
    cfg, sources = _r16_repo(
        tmp_path,
        docs=FAULT_DOCS + "| `demo.renamed` | `pkg/mod.py` | gone |\n",
    )
    msgs = [
        f.message
        for f in analyze_project(sources, cfg, rules=("R16",)).findings
    ]
    assert any(
        "documented fault site 'demo.renamed' matches no" in m for m in msgs
    )


# ------------------------------------------- incremental cache


def test_cache_results_match_and_edits_reparse(tmp_path):
    cfg = _mini_repo(tmp_path)
    cold = analyze_paths(config=cfg, cache=True)
    assert [f.rule for f in cold.active] == ["R4"]
    assert os.path.isdir(os.path.join(str(tmp_path), ".photon-lint-cache"))

    warm = analyze_paths(config=cfg, cache=True)
    assert [f.to_dict() for f in warm.findings] == [
        f.to_dict() for f in cold.findings
    ]

    # an edit must be re-analyzed, not served from cache
    (tmp_path / "pkg" / "mod.py").write_text("x = 1\n")
    edited = analyze_paths(config=cfg, cache=True)
    assert edited.active == []

    # and an aux-input edit (the fault docs) invalidates the run cache too
    (tmp_path / "pkg" / "mod.py").write_text(FAULTY_MOD)
    first = analyze_paths(config=cfg, cache=True)
    assert any(f.rule == "R16" for f in first.active)
    (tmp_path / "README.md").write_text(FAULT_DOCS)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_demo.py").write_text('SITE = "demo.boundary"\n')
    from photon_ml_tpu.analysis.engine import write_fault_inventory

    write_fault_inventory(cfg)
    refreshed = analyze_paths(config=cfg, cache=True)
    assert refreshed.active == []


def test_cache_corrupt_entry_is_a_miss(tmp_path):
    cfg = _mini_repo(tmp_path)
    analyze_paths(config=cfg, cache=True)
    cache_dir = os.path.join(str(tmp_path), ".photon-lint-cache")
    for name in os.listdir(cache_dir):
        with open(os.path.join(cache_dir, name), "wb") as f:
            f.write(b"not a pickle")
    again = analyze_paths(config=cfg, cache=True)
    assert [f.rule for f in again.active] == ["R4"]


# ------------------------------------------- config-error exit codes


def test_cli_unknown_thread_entrypoint_exits_2(tmp_path, capsys):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "mod.py").write_text("x = 1\n")
    (tmp_path / "pyproject.toml").write_text(
        '[tool.photon-lint]\npaths = ["pkg"]\n'
        'thread_entrypoints = ["pkg/mod.py::Nope.run"]\n'
    )
    assert lint_main(["--config", str(tmp_path / "pyproject.toml")]) == 2
    err = capsys.readouterr().err
    assert "config error:" in err
    assert "thread_entrypoints:" in err
    assert "does not name a known function" in err


def test_cli_malformed_annotation_exits_2(tmp_path, capsys):
    _mini_repo(
        tmp_path,
        source=(
            "import threading\n\nL1 = threading.Lock()\n\n\n"
            "# photon: lock-order[L1 >]\ndef f():\n    with L1:\n        pass\n"
        ),
    )
    py = _write_pyproject(tmp_path)
    assert lint_main(["--config", py]) == 2
    err = capsys.readouterr().err
    assert "config error:" in err
    assert "annotation:" in err and "malformed" in err


def test_cli_unreadable_file_exits_1_not_2(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    os.symlink(str(tmp_path / "gone.py"), str(pkg / "mod.py"))
    py = _write_pyproject(tmp_path)
    assert lint_main(["--config", py]) == 1
    err = capsys.readouterr().err
    assert "parse error:" in err and "cannot read" in err
    assert "config error:" not in err


def test_unused_lock_order_and_static_arg_annotations_are_r12(tmp_path):
    cfg = _mini_repo(
        tmp_path,
        textwrap.dedent(
            """
            import threading

            L1 = threading.Lock()
            L2 = threading.Lock()


            # photon: lock-order[L1 < L2]
            def f():
                with L1:
                    pass
            """
        ),
    )
    result = analyze_paths(config=cfg)
    assert [f.rule for f in result.active] == ["R12"]
    assert (
        "lock-order annotation suppresses no R13" in result.active[0].message
    )
