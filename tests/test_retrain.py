"""Continuous-training chain tests (game/incremental.py + cli/retrain.py):
entity-merge bitwise carry, chain-vs-scratch equivalence, the no-degrade
promotion gate, kill/resume and torn-publish drills, prior-index
compatibility, and the day-partitioned CLI driver end to end."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import obs
from photon_ml_tpu.estimators.game_estimator import (
    CoordinateConfig,
    GameEstimator,
    GameTransformer,
)
from photon_ml_tpu.game import incremental
from photon_ml_tpu.game.problem import GLMOptimizationConfig
from photon_ml_tpu.io.index_map import IndexMap, feature_key
from photon_ml_tpu.io.model_io import (
    check_prior_compatibility,
    load_game_model,
    save_game_model,
)
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, LogisticRegressionModel
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.robust import faults
from photon_ml_tpu.robust.faults import SimulatedKill
from photon_ml_tpu.serving import refresh
from photon_ml_tpu.testing import generate_mixed_effect_data
from photon_ml_tpu.testing.generators import mixed_data_to_raw_dataset

D_FIXED = 6
N_ENT, D_RE = 10, 3
SPECS = ["AUC", "AUC:userId"]


@pytest.fixture
def run():
    r = obs.RunTelemetry()
    with obs.use_run(r):
        yield r


def _cfg():
    return GLMOptimizationConfig(
        optimizer=OptimizerConfig(tolerance=1e-9, max_iterations=200),
        regularization=RegularizationContext("L2"),
        reg_weight=1.0,
    )


def _coords():
    return [
        CoordinateConfig(name="global", feature_shard="global", config=_cfg()),
        CoordinateConfig(
            name="per-user",
            feature_shard="userShard",
            random_effect_type="userId",
            config=_cfg(),
        ),
    ]


def _estimator(n_cd_iterations=2):
    return GameEstimator(
        task="logistic_regression",
        coordinate_configs=_coords(),
        n_cd_iterations=n_cd_iterations,
        evaluator_specs=SPECS,
        dtype=jnp.float64,
    )


def _index_maps():
    # add_intercept=False: the generated dense features have no intercept
    # column, so the index dim must equal d_fixed for save/load round trips
    return {
        "global": IndexMap.from_name_terms(
            [(f"g{j}", "") for j in range(D_FIXED)], add_intercept=False
        ),
        "userShard": IndexMap.from_name_terms(
            [(f"u{j}", "") for j in range(D_RE)], add_intercept=False
        ),
    }


@pytest.fixture(scope="module")
def feed():
    """Three 'days' of GLMix rows + a held-out validation set. Day 2 only
    touches the first half of the entities, so the second half must carry
    forward bitwise through the merge."""
    data = generate_mixed_effect_data(
        task="logistic_regression",
        n=1100,
        d_fixed=D_FIXED,
        re_specs={"userId": (N_ENT, D_RE)},
        seed=7,
    )
    raw = mixed_data_to_raw_dataset(data)
    # one generating model; held-out validation rows come from the SAME
    # model so the learned effects actually transfer (test_estimators idiom)
    ents = data.entity_ids["userId"]
    first_half = np.isin(ents, [f"e{k}" for k in range(N_ENT // 2)])
    rows = np.arange(data.n)
    in_day1 = (rows >= 350) & (rows < 700)
    day0 = raw.subset(rows[:350])
    day1 = raw.subset(rows[in_day1 & first_half])
    day2 = raw.subset(rows[in_day1 & ~first_half])
    return {
        "union": raw.subset(rows[:700]),
        "days": [("20260101", day0), ("20260102", day1), ("20260103", day2)],
        "validation": raw.subset(rows[700:]),
    }


# -- the entity merge --------------------------------------------------------


def _re(ids, idx, val, variances=None):
    return RandomEffectModel(
        random_effect_type="userId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=np.asarray(ids, dtype=object),
        coef_indices=jnp.asarray(idx, jnp.int32),
        coef_values=jnp.asarray(val, jnp.float64),
        variances=None if variances is None else jnp.asarray(variances, jnp.float64),
    )


def test_grow_random_effect_bitwise_carry_and_growth():
    prior = _re(["uA", "uB"], [[0, 1], [2, -1]], [[0.5, -1.5], [2.25, 0.0]])
    update = _re(["uB", "uD"], [[0, 1, 2], [1, -1, -1]], [[9.0, 8.0, 7.0], [3.5, 0.0, 0.0]])
    out = incremental.grow_random_effect(prior, update)
    assert list(out.entity_ids) == ["uA", "uB", "uD"]
    # uA untouched: bitwise carry, widened with the -1 sentinel / 0.0 pad
    np.testing.assert_array_equal(np.asarray(out.coef_indices[0]), [0, 1, -1])
    np.testing.assert_array_equal(np.asarray(out.coef_values[0]), [0.5, -1.5, 0.0])
    # uB re-solved in place, uD appended (model growth)
    np.testing.assert_array_equal(np.asarray(out.coef_indices[1]), [0, 1, 2])
    np.testing.assert_array_equal(np.asarray(out.coef_values[1]), [9.0, 8.0, 7.0])
    np.testing.assert_array_equal(np.asarray(out.coef_values[2]), [3.5, 0.0, 0.0])
    assert out.variances is None
    # untouched entities score identically through the merged model
    assert out.entity_row("uA") == 0 and out.entity_row("uD") == 2


def test_grow_random_effect_variances_merge_only_when_both_sides_have_them():
    prior = _re(["uA"], [[0]], [[1.0]], variances=[[0.25]])
    update = _re(["uB"], [[1]], [[2.0]], variances=[[0.5]])
    both = incremental.grow_random_effect(prior, update)
    np.testing.assert_array_equal(np.asarray(both.variances), [[0.25], [0.5]])
    # a means-only update invalidates the prior's stale variances
    mixed = incremental.grow_random_effect(prior, _re(["uB"], [[1]], [[2.0]]))
    assert mixed.variances is None


def test_grow_random_effect_refuses_mismatched_models():
    prior = _re(["uA"], [[0]], [[1.0]])
    other = RandomEffectModel(
        random_effect_type="itemId",
        feature_shard="userShard",
        task="logistic_regression",
        entity_ids=np.asarray(["i1"], dtype=object),
        coef_indices=jnp.asarray([[0]], jnp.int32),
        coef_values=jnp.asarray([[1.0]], jnp.float64),
    )
    with pytest.raises(ValueError, match="different types"):
        incremental.grow_random_effect(prior, other)


def test_merge_models_counts_touched_entities():
    fe = FixedEffectModel(
        model=LogisticRegressionModel(Coefficients(jnp.zeros(3))),
        feature_shard="global",
    )
    prior = GameModel(
        models={"global": fe, "per-user": _re(["uA", "uB"], [[0], [1]], [[1.0], [2.0]])},
        task="logistic_regression",
    )
    update = GameModel(
        models={"global": fe, "per-user": _re(["uB", "uC"], [[0], [1]], [[5.0], [6.0]])},
        task="logistic_regression",
    )
    merged, touched = incremental.merge_models(prior, update)
    assert touched == {"per-user": 2}
    assert merged.models["per-user"].num_entities == 3
    # no prior: the whole update counts as touched
    _, touched0 = incremental.merge_models(None, update)
    assert touched0 == {"per-user": 2}


# -- the chain ---------------------------------------------------------------


def _auc(model, validation):
    _, ev = GameTransformer(model=model, dtype=jnp.float64).transform(
        validation, ["AUC"]
    )
    return ev.metrics["AUC"]


def test_chain_matches_scratch_union_and_touches_fraction(feed, run, tmp_path):
    res = incremental.run_chain(
        _estimator(),
        feed["days"],
        feed["validation"],
        chain_dir=str(tmp_path / "chain"),
        evaluator_specs=SPECS,
        # entity-partitioned day slices are FE-biased by construction; a
        # small margin lets the chain advance through the tiny dips
        gate_margin=0.05,
        dtype=jnp.float64,
    )
    assert [r.accepted for r in res.ledger].count(True) == 3
    # a daily from-scratch retrain refits the whole union every day; the
    # chain touches only each day's rows
    assert res.rows_touched < res.rows_cumulative
    assert res.rows_touched_fraction == pytest.approx(
        res.rows_touched / res.rows_cumulative
    )
    scratch = _estimator().fit(feed["union"])
    scratch_auc = _auc(scratch[-1].model, feed["validation"])
    chain_auc = _auc(res.model, feed["validation"])
    assert chain_auc > 0.6
    assert abs(chain_auc - scratch_auc) < 0.05


def test_chain_untouched_entities_carry_bitwise(feed, run, tmp_path):
    days = feed["days"]
    est = _estimator()
    first = incremental.run_chain(
        est, days[:1], feed["validation"], chain_dir=str(tmp_path / "a"),
        evaluator_specs=SPECS, gate_margin=0.05, dtype=jnp.float64,
    )
    chained = incremental.run_chain(
        est, days[:2], feed["validation"], chain_dir=str(tmp_path / "b"),
        evaluator_specs=SPECS, gate_margin=0.05, dtype=jnp.float64,
    )
    # day 2 only touches e0..e4; e5.. carry forward bitwise from day 1
    day0_re = first.model.models["per-user"]
    re2 = chained.model.models["per-user"]
    assert chained.ledger[1].accepted
    untouched = [f"e{k}" for k in range(N_ENT // 2, N_ENT)]
    carried = [e for e in untouched if day0_re.entity_row(e) >= 0]
    assert carried, "no untouched entities materialized on day 1"
    S0 = day0_re.coef_indices.shape[1]
    for e in carried:
        r0, r2 = day0_re.entity_row(e), re2.entity_row(e)
        assert r2 >= 0
        np.testing.assert_array_equal(
            np.asarray(re2.coef_indices[r2])[:S0],
            np.asarray(day0_re.coef_indices[r0]),
        )
        np.testing.assert_array_equal(
            np.asarray(re2.coef_values[r2])[:S0],
            np.asarray(day0_re.coef_values[r0]),
        )
        np.testing.assert_array_equal(np.asarray(re2.coef_indices[r2])[S0:], -1)


def test_gate_blocks_degraded_day_and_counts_refusal(feed, run, tmp_path):
    day0 = feed["days"][0]
    poisoned_raw = feed["days"][1][1]
    # flip the day's labels: the candidate learns the inverted signal and
    # must degrade AUC on the held-out set
    import dataclasses as _dc

    poisoned_raw = _dc.replace(poisoned_raw, labels=1.0 - poisoned_raw.labels)
    srv = str(tmp_path / "serving")
    res = incremental.run_chain(
        _estimator(),
        [day0, ("20260102", poisoned_raw)],
        feed["validation"],
        chain_dir=str(tmp_path / "chain"),
        serving_root=srv,
        evaluator_specs=SPECS,
        dtype=jnp.float64,
    )
    assert res.ledger[0].accepted and res.ledger[0].published
    assert not res.ledger[1].accepted
    assert res.ledger[1].reason.startswith("degraded:")
    assert res.ledger[1].snapshot is None
    # the poisoned day never reaches the live store: day 1 keeps serving
    assert refresh.current_snapshot(srv) == res.ledger[0].snapshot
    # the live model is still day 1's (the chain did not advance)
    np.testing.assert_array_equal(
        np.asarray(res.model.models["per-user"].coef_values),
        np.asarray(
            incremental.run_chain(
                _estimator(), [day0], feed["validation"],
                chain_dir=str(tmp_path / "solo"), evaluator_specs=SPECS,
                dtype=jnp.float64,
            ).model.models["per-user"].coef_values
        ),
    )
    snap = run.registry.snapshot()
    rejected = [
        m for m in snap if m["name"] == "photon_retrain_rejected_total"
    ]
    assert rejected and rejected[0].get("labels", {}).get("reason") == res.ledger[1].reason
    days_m = {
        m.get("labels", {}).get("outcome"): m["value"]
        for m in snap
        if m["name"] == "photon_retrain_days_total"
    }
    assert days_m["rejected"] == 1


def test_gate_refuses_non_finite_candidate(feed, run):
    fe = FixedEffectModel(
        model=LogisticRegressionModel(
            Coefficients(jnp.asarray([np.nan] * D_FIXED))
        ),
        feature_shard="global",
    )
    bad = GameModel(models={"global": fe}, task="logistic_regression")
    decision = incremental.no_degrade_gate(
        bad, None, feed["validation"], ["AUC"], dtype=jnp.float64
    )
    assert not decision.accepted and decision.reason == "non-finite"


def test_gate_first_publish_without_live_model(feed, run, tmp_path):
    res = incremental.run_chain(
        _estimator(), feed["days"][:1], feed["validation"],
        chain_dir=str(tmp_path / "chain"), evaluator_specs=SPECS,
        dtype=jnp.float64,
    )
    assert res.ledger[0].accepted and res.ledger[0].reason == "first-publish"


# -- failure drills ----------------------------------------------------------


def _chain_days_json(chain_dir):
    with open(os.path.join(chain_dir, incremental.CHAIN_STATE_NAME)) as f:
        return json.load(f)["days"]


def test_kill_between_days_resumes_identical_ledger(feed, run, tmp_path):
    kw = dict(
        evaluator_specs=SPECS, dtype=jnp.float64, index_maps=_index_maps()
    )
    baseline_dir = str(tmp_path / "baseline")
    incremental.run_chain(
        _estimator(), feed["days"], feed["validation"],
        chain_dir=baseline_dir, **kw,
    )

    drilled_dir = str(tmp_path / "drilled")
    faults.configure("retrain.day:kill:2")
    try:
        with pytest.raises(SimulatedKill):
            incremental.run_chain(
                _estimator(), feed["days"], feed["validation"],
                chain_dir=drilled_dir, **kw,
            )
    finally:
        faults.clear()
    # the crash-between-days drill: day 1's decision is already durable
    assert len(_chain_days_json(drilled_dir)) == 1
    res = incremental.run_chain(
        _estimator(), feed["days"], feed["validation"],
        chain_dir=drilled_dir, **kw,
    )
    assert len(res.ledger) == 3
    # the resumed ledger is bit-exact against the uninterrupted run's
    assert _chain_days_json(drilled_dir) == _chain_days_json(baseline_dir)


def test_midday_kill_resumes_via_boundary_checkpoints(feed, run, tmp_path):
    kw = dict(
        evaluator_specs=SPECS, dtype=jnp.float64, index_maps=_index_maps(),
        checkpoint_every=1,
    )
    baseline_dir = str(tmp_path / "baseline")
    incremental.run_chain(
        _estimator(), feed["days"][:2], feed["validation"],
        chain_dir=baseline_dir, **kw,
    )

    drilled_dir = str(tmp_path / "drilled")
    # 2 coordinates x 2 sweeps = 4 boundaries/day; boundary 6 is mid-day-2
    faults.configure("cd.boundary:kill:6")
    try:
        with pytest.raises(SimulatedKill):
            incremental.run_chain(
                _estimator(), feed["days"][:2], feed["validation"],
                chain_dir=drilled_dir, **kw,
            )
    finally:
        faults.clear()
    state = json.load(
        open(os.path.join(drilled_dir, incremental.CHAIN_STATE_NAME))
    )
    assert state["in_progress"] == "20260102"
    # the day's boundary checkpoints identify their chain position on their
    # own: manifests carry the day label and the decided ledger so far
    day_dir = os.path.join(drilled_dir, "checkpoints", "day-0001")
    manifests = [
        json.load(open(os.path.join(day_dir, d, "MANIFEST.json")))
        for d in sorted(os.listdir(day_dir))
        if os.path.isdir(os.path.join(day_dir, d))
    ]
    assert manifests
    assert all(m["chain_day"] == "20260102" for m in manifests)
    assert all(len(m["chain_ledger"]) == 1 for m in manifests)

    res = incremental.run_chain(
        _estimator(), feed["days"][:2], feed["validation"],
        chain_dir=drilled_dir, **kw,
    )
    assert len(res.ledger) == 2 and res.ledger[1].day == "20260102"
    assert _chain_days_json(drilled_dir) == _chain_days_json(baseline_dir)


def test_torn_publish_keeps_old_snapshot_and_repairs_next_cycle(
    feed, run, tmp_path
):
    srv = str(tmp_path / "serving")
    chain = str(tmp_path / "chain")
    kw = dict(
        evaluator_specs=SPECS, dtype=jnp.float64, index_maps=_index_maps(),
        serving_root=srv,
    )
    faults.configure("retrain.publish:io:1")
    try:
        res = incremental.run_chain(
            _estimator(), feed["days"][:1], feed["validation"],
            chain_dir=chain, **kw,
        )
    finally:
        faults.clear()
    # the decision is durable, the publish is not: nothing serves yet
    assert res.ledger[0].accepted and not res.ledger[0].published
    assert refresh.current_snapshot(srv) is None
    snap = run.registry.snapshot()
    assert any(
        m["name"] == "photon_swallowed_errors_total"
        and m.get("labels", {}).get("site") == "retrain.publish"
        for m in snap
    )

    # next cycle repairs the store WITHOUT retraining the decided day
    def boom():
        raise AssertionError("decided day must not reload on repair")

    res2 = incremental.run_chain(
        _estimator(), [("20260101", boom)], feed["validation"],
        chain_dir=chain, **kw,
    )
    assert refresh.current_snapshot(srv) == res.ledger[0].snapshot
    assert res2.ledger[0].published
    snap = run.registry.snapshot()
    published = [
        m for m in snap if m["name"] == "photon_retrain_published_total"
    ]
    assert published and published[0]["value"] == 1


# -- prior-index compatibility (cli train --incremental-training) ------------


def _save_prior(tmp_path, index_maps):
    model = GameModel(
        models={
            "global": FixedEffectModel(
                model=LogisticRegressionModel(
                    Coefficients(jnp.arange(1.0, D_FIXED + 1))
                ),
                feature_shard="global",
            ),
            "per-user": _re(["uA"], [[0, 1, 2]], [[1.0, 2.0, 3.0]]),
        },
        task="logistic_regression",
    )
    d = str(tmp_path / "prior")
    save_game_model(d, model, index_maps)
    return d


def test_prior_compatibility_exact_remap_refused(tmp_path):
    imaps = _index_maps()
    model_dir = _save_prior(tmp_path, imaps)

    assert check_prior_compatibility(model_dir, imaps) == {
        "global": "exact", "userShard": "exact",
    }
    # permuted index: same key set, different layout -> lossless remap
    # (from_name_terms sorts keys, so permute via an explicit key->index dict)
    permuted = dict(imaps)
    permuted["global"] = IndexMap(
        {feature_key(f"g{j}", ""): D_FIXED - 1 - j for j in range(D_FIXED)}
    )
    assert check_prior_compatibility(model_dir, permuted)["global"] == "remap"
    # an index missing a prior feature would silently zero its prior mean:
    # refused, not remapped
    shrunk = dict(imaps)
    shrunk["global"] = IndexMap.from_name_terms(
        [(f"g{j}", "") for j in range(D_FIXED - 1)]
    )
    with pytest.raises(
        ValueError,
        match="prior model features absent from the current feature index",
    ):
        check_prior_compatibility(model_dir, shrunk)
    # a shard with no current index at all is the same refusal
    with pytest.raises(
        ValueError,
        match="prior model features absent from the current feature index",
    ):
        check_prior_compatibility(model_dir, {"global": imaps["global"]})


def test_fingerprint_stamped_into_model_meta(tmp_path):
    imaps = _index_maps()
    model_dir = _save_prior(tmp_path, imaps)
    meta = json.load(open(os.path.join(model_dir, "model-metadata.json")))
    fp = meta["featureIndexFingerprint"]
    assert set(fp["shards"]) == {"global", "userShard"}
    assert fp["shards"]["global"]["size"] == D_FIXED
    # the remap/exact fast path keys off these digests
    assert fp["shards"]["global"]["keys"] != fp["shards"]["userShard"]["keys"]


def test_prior_round_trips_through_chain_store(feed, run, tmp_path):
    """The chain's models/day-* store must reload bit-exact: resume quality
    depends on it (day k+1 warm-starts from the reloaded model)."""
    imaps = _index_maps()
    res = incremental.run_chain(
        _estimator(), feed["days"][:1], feed["validation"],
        chain_dir=str(tmp_path / "chain"), evaluator_specs=SPECS,
        dtype=jnp.float64, index_maps=imaps,
    )
    reloaded = load_game_model(
        os.path.join(str(tmp_path / "chain"), "models", "day-20260101"),
        imaps,
        task="logistic_regression",
    )
    np.testing.assert_array_equal(
        np.asarray(res.model.models["global"].model.coefficients.means),
        np.asarray(reloaded.models["global"].model.coefficients.means),
    )
    re0, re1 = res.model.models["per-user"], reloaded.models["per-user"]
    assert sorted(map(str, re0.entity_ids)) == sorted(map(str, re1.entity_ids))
    for e in map(str, re0.entity_ids):
        a = np.asarray(re0.coef_values[re0.entity_row(e)])
        b = np.asarray(re1.coef_values[re1.entity_row(e)])
        np.testing.assert_array_equal(np.sort(a), np.sort(b))
