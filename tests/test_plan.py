"""Exhaustive combination sweep over the execution planner.

Every point in (kind x layout x dtype x mesh x processes x streaming x
pipelining x variance x regularization x lanes) must either resolve to a
typed ExecutionPlan OR raise exactly one PlanError whose message carries a
ledger-pinned fragment (refusals.json, the machine-readable mirror of the
README support matrix) — never a deep-stack NotImplementedError out of
parallel/mesh.py or game/data.py, and never a second error flavor for the
same combination.

Deliberately imports no jax: the planner must stay resolvable with no
accelerator runtime (cli train --explain-plan dry-runs on any host).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from pathlib import Path
from typing import Optional

import pytest

from photon_ml_tpu.plan import (
    ExecutionPlan,
    PlanError,
    check_multiprocess_mesh,
    resolve,
)

ROOT = Path(__file__).resolve().parents[1]

FRAGMENTS = [
    e["fragment"]
    for e in json.loads((ROOT / "refusals.json").read_text())["refusals"]
]


@dataclasses.dataclass
class _Reg:
    reg_type: str = "L2"


@dataclasses.dataclass
class _Cfg:
    variance_type: str = "NONE"
    down_sampling_rate: float = 1.0
    regularization: _Reg = dataclasses.field(default_factory=_Reg)


@dataclasses.dataclass
class _CC:
    """CoordinateConfig-shaped duck object — exactly the attribute surface
    the planner documents; using it (instead of the real CoordinateConfig)
    proves the planner needs no estimator/jax import."""

    name: str = "c0"
    feature_shard: str = "global"
    layout: str = "auto"
    feature_dtype: Optional[str] = None
    hbm_budget_mb: Optional[int] = None
    is_random_effect: bool = False
    config: _Cfg = dataclasses.field(default_factory=_Cfg)
    normalization: Optional[object] = None
    regularize_by_prior: bool = False


KINDS = (False, True)  # is_random_effect
LAYOUTS = ("auto", "dense", "ell", "coo", "tiled")
DTYPES = (None, "bfloat16")
MESHES = (None, {"data": 8, "model": 1}, {"data": 4, "model": 2})
PROCESSES = (1, 2)
BUDGETS = (None, 0, 64)
DEPTHS = (1, 2)
VARIANCES = ("NONE", "SIMPLE")
REGS = ("L2", "L1")
LANES = (1, 4)


def _combos():
    return itertools.product(
        KINDS, LAYOUTS, DTYPES, MESHES, PROCESSES, BUDGETS, DEPTHS,
        VARIANCES, REGS, LANES,
    )


def test_every_combination_plans_or_refuses_with_ledger_message():
    n_plans = n_refusals = 0
    for (is_re, layout, dtype, mesh, n_proc, budget, depth, variance,
         reg, lanes) in _combos():
        cc = _CC(
            layout=layout,
            feature_dtype=dtype,
            hbm_budget_mb=budget,
            is_random_effect=is_re,
            config=_Cfg(variance_type=variance, regularization=_Reg(reg)),
        )
        label = (
            f"kind={'re' if is_re else 'fe'} layout={layout} dtype={dtype} "
            f"mesh={mesh} procs={n_proc} budget={budget} depth={depth} "
            f"variance={variance} reg={reg} lanes={lanes}"
        )
        try:
            # any exception other than PlanError (a NotImplementedError
            # leaking up from a deep layer, a TypeError from a missing
            # attribute) propagates and fails the sweep outright
            plan = resolve(
                [cc],
                mesh=mesh,
                n_processes=n_proc,
                pipeline_depth=depth,
                trial_lanes=lanes,
                distributed=n_proc > 1,
            )
        except PlanError as e:
            n_refusals += 1
            assert isinstance(e, ValueError), label
            assert any(f in str(e) for f in FRAGMENTS), (
                f"refusal message not pinned in refusals.json ({label}): {e}"
            )
            continue
        n_plans += 1
        assert isinstance(plan, ExecutionPlan), label
        (cp,) = plan.coordinates
        assert cp.residency == ("streamed" if budget is not None else "resident"), label
        assert cp.pipelined == (depth > 1), label
        assert plan.n_processes == n_proc and plan.pipeline_depth == depth, label
        assert plan.trial_lanes == lanes, label
        if mesh is None:
            assert cp.sharding == "single-device", label
        elif budget is not None:
            assert cp.sharding == (
                "entity-sharded (host-resident blocks)"
                if is_re
                else "host-sharded rows (streamed slices)"
            ), label
        # the plan document must be JSON-serializable and printable as-is
        json.dumps(plan.to_dict())
        assert plan.pretty().startswith("execution plan"), label
    # the sweep covers both outcomes at scale, not a vacuous pass
    assert n_plans > 100 and n_refusals > 100, (n_plans, n_refusals)


def test_planner_preempts_deep_stack_runtime_refusals():
    """The combinations parallel/mesh.py would reject mid-build (with a
    NotImplementedError deep inside shard_batch/shard_coefficients) must be
    refused by the planner up front, with the same ledger message."""
    mesh = {"data": 8, "model": 1}
    with pytest.raises(
        PlanError, match="shard_batch does not support the column-sorted COO"
    ):
        resolve([_CC(layout="coo")], mesh=mesh, n_processes=1)
    with pytest.raises(PlanError, match="multi-process ELL sharding"):
        resolve([_CC(layout="ell")], mesh=mesh, n_processes=2, distributed=True)
    with pytest.raises(
        PlanError, match="model-axis sharding across processes"
    ):
        resolve(
            [_CC()], mesh={"data": 4, "model": 2}, n_processes=2,
            distributed=True,
        )
    with pytest.raises(
        PlanError, match="multi-process training requires a device mesh"
    ):
        check_multiprocess_mesh(2, None)


def test_newly_legal_compositions_resolve():
    """The three compositions this planner legalized (formerly ledger
    refusals): streamed FE x mesh/multi-process, streamed RE x mesh
    sharding, pipeline depth >= 2 x distributed."""
    mesh = {"data": 8, "model": 1}
    plan = resolve(
        [
            _CC(name="global", hbm_budget_mb=0),
            _CC(name="per-user", hbm_budget_mb=0, is_random_effect=True),
        ],
        mesh=mesh,
        n_processes=2,
        pipeline_depth=2,
        distributed=True,
    )
    fe, re_ = plan.coordinates
    assert fe.residency == re_.residency == "streamed"
    assert fe.sharding == "host-sharded rows (streamed slices)"
    assert re_.sharding == "entity-sharded (host-resident blocks)"
    assert fe.pipelined and re_.pipelined and plan.distributed
    # streamed ELL is legal multi-process (per-host widths are private)
    plan = resolve(
        [_CC(layout="ell", hbm_budget_mb=64)], mesh=mesh, n_processes=2,
        distributed=True,
    )
    assert plan.coordinates[0].residency == "streamed"


def test_streamed_geometry_carries_slice_shape():
    """With a known feature dim the FE plan carries concrete slice geometry
    (the numbers --explain-plan prints)."""
    plan = resolve(
        [_CC(name="g", hbm_budget_mb=64)],
        mesh={"data": 8, "model": 1},
        n_processes=2,
        dims={"global": 1000},
    )
    g = plan.coordinates[0].geometry
    assert g["budget_bytes"] == 64 << 20
    assert g["slice_row_bytes"] == 1000 * 4
    assert g["rows_per_slice"] >= 1
    assert g["hosts_streaming"] == 2
    # bfloat16 halves the slice row bytes
    plan16 = resolve(
        [_CC(name="g", hbm_budget_mb=64, feature_dtype="bfloat16")],
        mesh={"data": 8, "model": 1},
        n_processes=2,
        dims={"global": 1000},
    )
    assert plan16.coordinates[0].geometry["slice_row_bytes"] == 1000 * 2


def test_plan_error_is_a_value_error():
    # call sites that predate the planner catch ValueError; PlanError must
    # keep flowing through them
    assert issubclass(PlanError, ValueError)
