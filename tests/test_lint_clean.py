"""Tier-1 self-check: the package passes its own static analysis.

This is the CI gate in test form — the same configuration, baseline, and
rule set as ``python -m photon_ml_tpu.analysis``. A new unsuppressed finding
anywhere in the configured paths (photon_ml_tpu/ and bench.py) fails this
test with the finding list in the assertion message; fix it, suppress it
with a reasoned ``# photon: ignore[Rn]``, or (for a deliberate
grandfathering) add it to lint_baseline.json via --write-baseline."""

import os

from photon_ml_tpu.analysis import analyze_paths, load_baseline, load_config

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_is_lint_clean():
    config = load_config(pyproject=os.path.join(REPO_ROOT, "pyproject.toml"))
    baseline = load_baseline(config.baseline_path)
    result = analyze_paths(config=config, baseline=baseline)
    assert not result.parse_errors, result.parse_errors
    assert not result.active, "\n" + "\n".join(
        f"{f.file}:{f.line}:{f.col}: {f.rule} {f.message}\n    {f.code}"
        for f in result.active
    )
    assert result.files_scanned > 50  # the walk really covered the package
