"""Tier-1 self-check: the package passes its own static analysis.

This is the CI gate in test form — the same configuration, baseline, and
rule set as ``python -m photon_ml_tpu.analysis``. A new unsuppressed finding
anywhere in the configured paths (photon_ml_tpu/ and bench.py) fails this
test with the finding list in the assertion message; fix it, suppress it
with a reasoned ``# photon: ignore[Rn]``, or (for a deliberate
grandfathering) add it to lint_baseline.json via --write-baseline."""

import os

import pytest

from photon_ml_tpu.analysis import analyze_paths, load_baseline, load_config
from photon_ml_tpu.analysis.engine import iter_python_files
from photon_ml_tpu.analysis.project import (
    analyze_project,
    render_refusal_inventory,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def config():
    return load_config(pyproject=os.path.join(REPO_ROOT, "pyproject.toml"))


@pytest.fixture(scope="module")
def package_sources(config):
    root = os.path.abspath(config.root)
    sources = {}
    for path in iter_python_files(config.paths, config):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            sources[rel] = f.read()
    return sources


def test_package_is_lint_clean(config):
    # analyze_paths with default paths is a FULL configured run, so this
    # gate covers the whole-program passes (R9-R12) too, not just R1-R8
    baseline = load_baseline(config.baseline_path)
    result = analyze_paths(config=config, baseline=baseline)
    assert not result.parse_errors, result.parse_errors
    assert not result.active, "\n" + "\n".join(
        f"{f.file}:{f.line}:{f.col}: {f.rule} {f.message}\n    {f.code}"
        for f in result.active
    )
    assert result.files_scanned > 50  # the walk really covered the package


def test_race_annotations_are_consulted(config, package_sources):
    """The R9 pass really runs and really validates: no annotation errors,
    and the package's guarded-by/thread-confined annotations are consumed
    by actual race findings (an unused one would be R12 upstream)."""
    res = analyze_project(package_sources, config, rules=("R9",))
    assert res.errors == []
    assert res.annotations, "expected race annotations in the package"
    assert res.used_annotations, "annotations exist but excuse no race"


def test_refusal_inventory_is_fresh(config, package_sources):
    """refusals.json must be byte-identical to a fresh regeneration, and
    every documented refusal must be enforced by at least one raise site."""
    res = analyze_project(package_sources, config, rules=("R10",))
    assert res.refusal_inventory is not None, "README ledger not found"
    want = render_refusal_inventory(res.refusal_inventory)
    inv_path = os.path.join(config.root, config.refusal_inventory)
    with open(inv_path, encoding="utf-8") as f:
        assert f.read() == want, "stale: run --write-refusal-inventory"
    for entry in res.refusal_inventory["refusals"]:
        assert entry["modules"], f"unenforced refusal: {entry['fragment']!r}"
        assert entry["exceptions"], entry["fragment"]


def test_dataflow_rules_are_zero_active(config, package_sources):
    """R13-R16 hold on the package itself with NO baseline net: no lock-order
    cycle, no resource leaked on any CFG path, no tracer hazard reachable
    from a @jit root, no fault-site drift."""
    res = analyze_project(
        package_sources, config, rules=("R13", "R14", "R15", "R16")
    )
    assert res.errors == []
    assert res.findings == [], "\n" + "\n".join(
        f"{f.file}:{f.line}: {f.rule} {f.message}" for f in res.findings
    )


def test_fault_inventory_is_fresh(config, package_sources):
    """faults.json must be byte-identical to a fresh regeneration (both
    directions: a new site or a deleted one is equally stale), and every
    inventoried site must name at least one declaring module."""
    from photon_ml_tpu.analysis.dataflow import (
        build_fault_inventory,
        extract_fault_sites,
        render_fault_inventory,
    )

    want = render_fault_inventory(
        build_fault_inventory(extract_fault_sites(package_sources))
    )
    inv_path = os.path.join(config.root, config.fault_inventory)
    with open(inv_path, encoding="utf-8") as f:
        assert f.read() == want, "stale: run --write-fault-inventory"
    doc = build_fault_inventory(extract_fault_sites(package_sources))
    assert doc["sites"], "expected fault sites in the package"
    for entry in doc["sites"]:
        assert entry["modules"], f"siteless entry: {entry['site']!r}"


def test_cached_lint_matches_uncached(config, tmp_path, monkeypatch):
    """--cache is a pure speedup: byte-identical findings, and the second
    run really is served from the run-level cache entry."""
    import dataclasses as _dc

    from photon_ml_tpu.analysis.engine import CACHE_DIR_NAME

    cfg = _dc.replace(config, root=config.root)
    plain = analyze_paths(config=cfg)
    monkeypatch.setattr(
        "photon_ml_tpu.analysis.engine.CACHE_DIR_NAME",
        str(tmp_path / CACHE_DIR_NAME),
    )
    cold = analyze_paths(config=cfg, cache=True)
    warm = analyze_paths(config=cfg, cache=True)
    for result in (cold, warm):
        assert [f.to_dict() for f in result.findings] == [
            f.to_dict() for f in plain.findings
        ]
        assert result.files_scanned == plain.files_scanned
