"""BASELINE config 2: linear regression with elastic net (OWL-QN) and
Poisson regression (TRON) on the reference's own Avro fixtures.

Fixtures (read-only, shipped with the reference's legacy-driver integ tests):
- linear_regression_train/val.avro  (1,000 / 1,000 rows, 6 features)
- poisson_test.avro                 (4,521 rows, 26 features)

Run:  python examples/glm_elasticnet.py [--out out-elasticnet]
Expect: elastic-net sparsifies the linear model at higher lambda; Poisson
TRON converges in <15 outer iterations (reference TRON defaults).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.utils.compile_cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

FIXTURES = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="out-elasticnet")
    args = ap.parse_args()

    from photon_ml_tpu.cli import glm

    results = {}

    t0 = time.time()
    glm.run(
        [
            "--input-data", f"{FIXTURES}/linear_regression_train.avro",
            "--validation-data", f"{FIXTURES}/linear_regression_val.avro",
            "--task", "linear_regression",
            "--optimizer", "OWLQN",
            "--regularization-type", "ELASTIC_NET",
            "--elastic-net-alpha", "0.5",
            "--regularization-weights", "0.01|0.1|1",
            "--evaluators", "RMSE",
            "--feature-shard", "name=global,bags=features",
            "--output-dir", os.path.join(args.out, "linear"),
        ]
    )
    with open(os.path.join(args.out, "linear", "summary.json")) as f:
        s = json.load(f)
    best = next(m for m in s["models"] if m["reg_weight"] == s["best_reg_weight"])
    results["linear-elasticnet"] = {
        "rmse": best["metrics"]["RMSE"],
        "best_lambda": s["best_reg_weight"],
        "wall_clock_s": round(time.time() - t0, 2),
    }

    t0 = time.time()
    glm.run(
        [
            "--input-data", f"{FIXTURES}/poisson_test.avro",
            "--validation-data", f"{FIXTURES}/poisson_test.avro",
            "--task", "poisson_regression",
            "--optimizer", "TRON",
            "--regularization-type", "L2",
            "--regularization-weights", "1",
            "--evaluators", "POISSON_LOSS",
            "--feature-shard", "name=global,bags=features",
            "--response-column", "response",
            "--output-dir", os.path.join(args.out, "poisson"),
        ]
    )
    with open(os.path.join(args.out, "poisson", "summary.json")) as f:
        s = json.load(f)
    m = s["models"][0]
    results["poisson-tron"] = {
        "poisson_loss": m["metrics"]["POISSON_LOSS"],
        "iterations": m["iterations"],
        "wall_clock_s": round(time.time() - t0, 2),
    }

    print(json.dumps(results))
    assert results["poisson-tron"]["iterations"] <= 15
    return results


if __name__ == "__main__":
    main()
