"""Lane-batched hyperparameter sweep: K tuning trials as lambda lanes of ONE
batched GLMix solve (``--trial-lanes``).

A deliberately bad regularization weight (5000 — crushes every coefficient)
is the grid baseline; a GP Bayesian sweep with ``--trial-lanes 4`` must beat
it on validation AUC within a fixed trial budget. The 8 trials run as 2
lane-batches of 4: each batch shares one data residency and one compiled
kernel (the per-lane reg weight is a vector operand, so the second batch
reuses the first batch's executable), and the GP proposes each batch jointly
via constant-liar qEI.

Data is self-contained synthetic mixed-effect (fixed + per-user random
effect) from the library's test generators, written to Avro so the run
drives the real CLI surface end-to-end.

RESUMABLE: the sweep records per-lane trial checkpoints in lane order under
``<out>/ckpt``. Kill this script mid-sweep and rerun the same command — it
replays the recorded trials into the GP and finishes the remaining budget
(Sobol chunking invariance keeps the candidate sequence identical).

Run:    python examples/sweep_lanes.py [--out out-sweep-lanes]
Expect: one JSON line; ``tuned_best_auc`` > ``grid_auc`` by >= 0.005.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.utils.compile_cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="out-sweep-lanes")
    ap.add_argument("--n", type=int, default=4000)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--lanes", type=int, default=4)
    args = ap.parse_args()

    from photon_ml_tpu.cli import train
    from photon_ml_tpu.io import write_avro_file
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO
    from photon_ml_tpu.testing import generate_mixed_effect_data
    from photon_ml_tpu.testing.generators import generate_game_records

    print(f"generating mixed-effect sweep data: n={args.n}", file=sys.stderr)
    data = generate_mixed_effect_data(
        n=args.n, d_fixed=10, re_specs={"userId": (40, 4)}, seed=11
    )
    recs = generate_game_records(data)
    n_val = args.n // 4
    tmp = tempfile.mkdtemp(prefix="sweep_lanes_")
    train_p = os.path.join(tmp, "train.avro")
    val_p = os.path.join(tmp, "val.avro")
    schema = {
        **TRAINING_EXAMPLE_AVRO,
        "fields": TRAINING_EXAMPLE_AVRO["fields"]
        + [
            {
                "name": "userFeatures",
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
        ],
    }
    write_avro_file(train_p, schema, recs[n_val:])
    write_avro_file(val_p, schema, recs[:n_val])

    common = [
        "--input-data", train_p,
        "--validation-data", val_p,
        "--task", "logistic_regression",
        "--feature-shard", "name=globalShard,bags=features",
        "--feature-shard", "name=userShard,bags=userFeatures",
        "--coordinate",
        # reg.weights=5000 on purpose: the sweep must recover from a grid
        # value that shrinks the model to near-intercept
        "name=global,shard=globalShard,optimizer=LBFGS,tolerance=1e-7,"
        "reg.type=L2,reg.weights=5000",
        "--coordinate",
        "name=per-user,shard=userShard,re.type=userId,optimizer=LBFGS,"
        "tolerance=1e-7,reg.type=L2,reg.weights=5000",
        "--coordinate-descent-iterations", "1",
        "--evaluators", "AUC",
        "--log-level", "WARNING",
    ]

    grid = train.run(common + ["--output-dir", os.path.join(args.out, "grid")])
    grid_auc = grid["best"]["metrics"]["AUC"]

    t0 = time.time()
    tuned = train.run(
        common
        + [
            "--hyper-parameter-tuning", "BAYESIAN",
            "--hyper-parameter-tuning-iter", str(args.iters),
            "--trial-lanes", str(args.lanes),
            "--output-mode", "TUNED",
            "--output-dir", os.path.join(args.out, "tuned"),
            # rerunning this script resumes the sweep from these records
            "--checkpoint-dir", os.path.join(args.out, "ckpt"),
        ]
    )
    wall = time.time() - t0

    aucs = [c["metrics"]["AUC"] for c in tuned["configs"]]
    result = {
        "config": "sweep-lanes-glmix",
        "grid_auc": grid_auc,
        "tuned_best_auc": max(aucs),
        "trials": len(aucs),
        "trial_lanes": args.lanes,
        "sweep_wall_s": round(wall, 2),
    }
    print(json.dumps(result))
    assert result["tuned_best_auc"] > result["grid_auc"] + 0.005, result
    return result


if __name__ == "__main__":
    main()
