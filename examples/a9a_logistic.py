"""BASELINE config 1: fixed-effect logistic regression on UCI Adult (a9a).

The reference anchors its regression tests on the Adult dataset family
(a1a in README examples; the in-repo fixture is a9a —
/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/README).
Train: a9a (32,561 rows, 123 features); test: a9a.t (16,281 rows).

Runs the legacy GLM driver (cli.glm, the reference Driver.scala pipeline) on
the LIBSVM text, L-BFGS with a small L2 grid, AUC/logistic-loss validation.

Run:  python examples/a9a_logistic.py [--out out-a9a]
Expect: AUC ~0.90 (public LIBLINEAR-family results for l2-regularized
logistic regression on a9a), wall-clock a few seconds on one chip.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.utils.compile_cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

A9A = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/a9a"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="out-a9a")
    ap.add_argument("--train", default=A9A)
    ap.add_argument("--test", default=A9A + ".t")
    args = ap.parse_args()

    from photon_ml_tpu.cli import glm

    t0 = time.time()
    glm.run(
        [
            "--input-data", args.train,
            "--validation-data", args.test,
            "--input-format", "LIBSVM",
            "--task", "logistic_regression",
            "--optimizer", "LBFGS",
            "--regularization-type", "L2",
            "--regularization-weights", "0.1|1|10",
            "--evaluators", "AUC,LOGISTIC_LOSS",
            "--output-dir", args.out,
        ]
    )
    wall = time.time() - t0
    with open(os.path.join(args.out, "summary.json")) as f:
        summary = json.load(f)
    best = next(
        m for m in summary["models"] if m["reg_weight"] == summary["best_reg_weight"]
    )
    n_train = 32561
    result = {
        "config": "a9a-logistic",
        "auc": best["metrics"]["AUC"],
        "logistic_loss": best["metrics"]["LOGISTIC_LOSS"],
        "best_lambda": summary["best_reg_weight"],
        "wall_clock_s": round(wall, 2),
        "examples_per_sec": round(n_train / wall, 1),
    }
    print(json.dumps(result))
    assert result["auc"] > 0.89, f"a9a AUC regression: {result['auc']}"
    return result


if __name__ == "__main__":
    main()
