"""BASELINE config 5: GP Bayesian hyperparameter auto-tuning.

Runs the GAME training CLI on the reference's heart fixture with
``--hyper-parameter-tuning BAYESIAN``: the explicit grid seeds the GP
(GameTrainingDriver.scala:666), each trial is a full train+validate, and the
best-metric-vs-trials curve is written alongside the summary. Also
demonstrates the smoothed-hinge SVM task on the same data.

Run:  python examples/autotune_bayesian.py [--out out-autotune] [--iters 8]
Expect: tuned logistic loss beats the (deliberately coarse) grid; the curve
is monotone non-increasing in best-so-far.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.utils.compile_cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

HEART = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input/heart.avro"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="out-autotune")
    ap.add_argument("--iters", type=int, default=8)
    args = ap.parse_args()

    from photon_ml_tpu.cli import train

    t0 = time.time()
    summary = train.run(
        [
            "--input-data", HEART,
            "--validation-data", HEART,
            "--task", "logistic_regression",
            "--feature-shard", "name=global,bags=features",
            "--coordinate",
            "name=global,shard=global,optimizer=LBFGS,reg.type=L2,"
            "reg.weights=1000",  # coarse grid on purpose: tuning must beat it
            "--normalization", "STANDARDIZATION",
            "--evaluators", "LOGISTIC_LOSS,AUC",
            "--hyper-parameter-tuning", "BAYESIAN",
            "--hyper-parameter-tuning-iter", str(args.iters),
            "--output-mode", "TUNED",
            "--output-dir", args.out,
        ]
    )
    wall = time.time() - t0

    losses = [c["metrics"]["LOGISTIC_LOSS"] for c in summary["configs"]]
    curve = []
    best = float("inf")
    for i, v in enumerate(losses):
        best = min(best, v)
        curve.append({"trial": i, "loss": v, "best_so_far": best})
    result = {
        "config": "gp-autotune-heart",
        "grid_loss": losses[0],
        "tuned_best_loss": best,
        "trials": len(losses),
        "wall_clock_s": round(wall, 2),
        "curve": curve,
    }
    with open(os.path.join(args.out, "best-metric-vs-trials.json"), "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({k: v for k, v in result.items() if k != "curve"}))
    assert result["tuned_best_loss"] < result["grid_loss"] - 0.05, result

    # smoothed-hinge SVM leg (the BASELINE config pairs the autotune with the
    # reference's experimental linear SVM task)
    svm = train.run(
        [
            "--input-data", HEART,
            "--validation-data", HEART,
            "--task", "smoothed_hinge_loss_linear_svm",
            "--feature-shard", "name=global,bags=features",
            "--coordinate",
            "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1",
            "--normalization", "STANDARDIZATION",
            "--evaluators", "AUC",
            "--output-dir", os.path.join(args.out, "svm"),
        ]
    )
    svm_auc = svm["best"]["metrics"]["AUC"]
    print(json.dumps({"config": "smoothed-hinge-svm-heart", "auc": svm_auc}))
    assert svm_auc > 0.85
    return result


if __name__ == "__main__":
    main()
